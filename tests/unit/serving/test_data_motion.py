"""Fleet data motion, replica side (ISSUE 16): handoff frame versioning and
CRC tamper rejection, zero-copy unpack, the streaming base64 resume-body
decoder, scheduler-level work stealing (queued + exported, token-identical
continuation), and peer prefix export framing."""

import base64
import io
import json
import struct
import tracemalloc
import zlib

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.ragged import handoff
from deepspeed_tpu.inference.v2.ragged.prefix_cache import digest_chain
from deepspeed_tpu.serving import (PrefixCacheConfig, RequestState,
                                   ServingConfig, ServingScheduler)
from deepspeed_tpu.serving.server import read_resume_body

MAX_STEPS = 400


def _run_until(sched, pred, max_steps=MAX_STEPS):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError(f"predicate not reached in {max_steps} steps")


def _prompt(n=9, vocab=64):
    return (np.arange(n) % vocab).tolist()


def _frame_with(version=1, flip_kv_byte=None, truncate=0, extra=None):
    """A hand-built v1 frame over synthetic KV — the tamper-test substrate
    (no engine needed: the framing layer is pure bytes)."""
    kv = np.arange(2 * 1 * 2 * 16 * 1 * 4, dtype=np.float32).reshape(
        (2, 1, 2, 16, 1, 4))
    raw = kv.tobytes()
    header = {
        "version": version,
        "uid": 7,
        "seen_tokens": 32,
        "tokens": list(range(32)),
        "extra": extra if extra is not None else {},
        "cache": {"block_size": 16, "num_layers": 1, "kv_heads": 1,
                  "head_dim": 4, "dtype": "float32"},
        "kv": {"shape": list(kv.shape), "dtype": "float32"},
        "kv_crc32": zlib.crc32(raw) & 0xFFFFFFFF,
    }
    hdr = json.dumps(header).encode()
    payload = bytearray(handoff.MAGIC + struct.pack("<I", len(hdr)) + hdr + raw)
    if flip_kv_byte is not None:
        off = len(handoff.MAGIC) + 4 + len(hdr) + flip_kv_byte
        payload[off] ^= 0xFF
    if truncate:
        payload = payload[:-truncate]
    return bytes(payload)


# ------------------------------------------------------------ frame tamper --
def test_handoff_roundtrips_and_carries_version():
    header, kv = handoff.unpack(_frame_with())
    assert header["version"] == 1 and 1 in handoff.SUPPORTED_VERSIONS
    assert kv.shape == (2, 1, 2, 16, 1, 4)
    assert handoff.CONTENT_TYPE == "application/x-dstpu-handoff"


def test_handoff_unknown_version_rejected_loudly():
    with pytest.raises(ValueError, match="unsupported handoff payload version"):
        handoff.unpack(_frame_with(version=3))
    with pytest.raises(ValueError, match="unsupported handoff payload version"):
        handoff.unpack(_frame_with(version=None))


def test_park_frame_version_matrix():
    """The v2 (parked) frame's versioned ``tier`` record: a v2 frame without
    it, with a malformed record, or with a tier version from the future is
    rejected LOUDLY by unpack — an older replica can never silently
    misinterpret a parked session. A v1 frame must not smuggle one in."""
    tier = {"v": handoff.TIER_FIELD_VERSION, "source": "host"}
    header, _ = handoff.unpack(_frame_with(version=2, extra={"tier": tier}))
    assert header["extra"]["tier"] == tier
    # v2 requires the record
    with pytest.raises(ValueError, match="requires a versioned extra.tier"):
        handoff.unpack(_frame_with(version=2))
    # malformed records
    for bad in ({"v": 0, "source": "host"}, {"v": 1}, {"source": "host"},
                {"v": "x", "source": "host"}, {"v": 1, "source": 3}, "host"):
        with pytest.raises(ValueError):
            handoff.unpack(_frame_with(version=2, extra={"tier": bad}))
    # a tier record from the future is a loud reject, not a silent downgrade
    with pytest.raises(ValueError, match="tier record version"):
        handoff.unpack(_frame_with(
            version=2,
            extra={"tier": {"v": handoff.TIER_FIELD_VERSION + 1,
                            "source": "host"}}))
    # v1 frames predate parking: a tier record there is a forgery
    with pytest.raises(ValueError, match="requires payload version >= 2"):
        handoff.unpack(_frame_with(version=1, extra={"tier": tier}))


def test_handoff_crc_flip_and_truncation_rejected():
    # a flipped byte anywhere in the CRC-covered KV region is a loud reject
    for off in (0, 100, 2 * 1 * 2 * 16 * 1 * 4 * 4 - 1):
        with pytest.raises(ValueError, match="checksum mismatch"):
            handoff.unpack(_frame_with(flip_kv_byte=off))
    with pytest.raises(ValueError, match="truncated"):
        handoff.unpack(_frame_with(truncate=5))
    with pytest.raises(ValueError, match="bad magic"):
        handoff.unpack(b"NOTDSTPU" + _frame_with()[8:])


def test_unpack_kv_aliases_payload_no_copy():
    """The zero-copy contract: the KV array returned by unpack aliases the
    payload buffer — no payload-sized intermediate is allocated."""
    payload = _frame_with()
    _, kv = handoff.unpack(payload)
    assert np.shares_memory(kv, np.frombuffer(payload, dtype=np.uint8))


# ----------------------------------------------- streaming base64 resume body --
def test_read_resume_body_decodes_payload_and_keeps_fields():
    payload = bytes(range(256)) * 33  # not 4-aligned in b64 chunks
    doc = {"max_new_tokens": 3, "payload": base64.b64encode(payload).decode(),
           "temperature": 0.5}
    body = json.dumps(doc).encode()
    out = read_resume_body(io.BytesIO(body), len(body))
    assert out["payload"] == payload
    assert out["max_new_tokens"] == 3 and out["temperature"] == 0.5


def test_read_resume_body_peak_memory_stays_near_1x():
    """The double-buffering fix (ISSUE satellite): decoding an N-byte payload
    must not hold wire (4/3x) + str (4/3x) + decoded (1x) simultaneously —
    peak traced allocation stays well under 2x (the old path was ~3.7x)."""
    n = 8 << 20
    payload = np.random.default_rng(0).integers(
        0, 256, n, dtype=np.uint8).tobytes()
    body = json.dumps({"payload": base64.b64encode(payload).decode(),
                       "max_new_tokens": 1}).encode()
    rfile = io.BytesIO(body)
    tracemalloc.start()
    try:
        out = read_resume_body(rfile, len(body))
        _, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    assert out["payload"] == payload
    assert peak < 1.5 * n, f"peak {peak} bytes for a {n}-byte payload"


def test_read_resume_body_truncation_is_value_error():
    payload = base64.b64encode(b"x" * 64).decode()
    body = json.dumps({"payload": payload}).encode()
    with pytest.raises(ValueError, match="truncated"):
        read_resume_body(io.BytesIO(body[:-10]), len(body))
    with pytest.raises(KeyError):
        body = json.dumps({"prompt": [1, 2]}).encode()
        read_resume_body(io.BytesIO(body), len(body))


# ------------------------------------------------------------ work stealing --
def test_steal_queued_request_regrants_token_identical(make_engine, llama_setup):
    """A still-queued request is released as ``queued``: finalized CANCELLED
    on the victim, and a from-scratch rerun elsewhere is trivially
    token-identical (same prompt, same seed)."""
    cfg, _, _ = llama_setup
    prompt = _prompt(11, cfg.vocab_size)
    victim = ServingScheduler(make_engine(), ServingConfig(), start=False)
    peer = ServingScheduler(make_engine(), ServingConfig(), start=False)
    req = victim.submit(prompt, max_new_tokens=4, seed=3)
    out = victim.request_steal(req.handle)
    assert out == {"status": "queued"}
    assert req.state is RequestState.CANCELLED and "stolen" in req.error
    assert victim.stats()["counters"]["steals"] == 1

    rerun = peer.submit(prompt, max_new_tokens=4, seed=3)
    _run_until(peer, lambda: rerun.finished)
    baseline = peer.submit(prompt, max_new_tokens=4, seed=3)
    _run_until(peer, lambda: baseline.finished)
    assert rerun.result(timeout=1) == baseline.result(timeout=1)
    victim.stop(drain=False)
    peer.stop(drain=False)


@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_steal_exported_mid_decode_resumes_token_identical(
        make_engine, llama_setup, temperature):
    """Early-decode steal: the victim exports the live sequence as a handoff
    frame; resuming it on a peer continues the EXACT token stream — greedy
    and seeded-sampled — with the victim's KV and sequence verifiably freed."""
    cfg, _, _ = llama_setup
    prompt = _prompt(13, cfg.vocab_size)
    n = 8
    peer = ServingScheduler(make_engine(), ServingConfig(), start=False)
    truth_req = peer.submit(prompt, max_new_tokens=n,
                            temperature=temperature, seed=1234)
    _run_until(peer, lambda: truth_req.finished)
    truth = truth_req.result(timeout=1)
    assert len(truth) == n

    victim = ServingScheduler(make_engine(), ServingConfig(), start=False)
    free0 = victim._engine.free_blocks
    req = victim.submit(prompt, max_new_tokens=n,
                        temperature=temperature, seed=1234)
    _run_until(victim, lambda: req.state is RequestState.DECODE
               and len(req.tokens) >= 3)
    out = victim.request_steal(req.handle)
    assert out["status"] == "exported"
    sent = out["sent"]
    assert sent >= 3 and list(req.tokens) == truth[:sent]
    assert req.state is RequestState.CANCELLED
    assert victim._engine.free_blocks == free0  # the export freed the donor KV
    assert victim._engine._state_manager.n_tracked_sequences == 0

    resumed = peer.submit_resume(out["payload"], max_new_tokens=n - sent,
                                 temperature=temperature, seed=1234)
    _run_until(peer, lambda: resumed.finished)
    assert resumed.result(timeout=1) == truth[sent:]  # bitwise continuation
    assert peer._engine._state_manager.n_tracked_sequences == 0
    victim.stop(drain=False)
    peer.stop(drain=False)


def test_steal_unknown_or_finished_handle_is_finished(make_engine, llama_setup):
    """Exactly-once: a handle the victim no longer owns (done, or never seen)
    answers ``finished`` and the request's terminal state is untouched."""
    cfg, _, _ = llama_setup
    sched = ServingScheduler(make_engine(), ServingConfig(), start=False)
    assert sched.request_steal("r999999") == {"status": "finished"}
    req = sched.submit(_prompt(7, cfg.vocab_size), max_new_tokens=2)
    _run_until(sched, lambda: req.finished)
    tokens = req.result(timeout=1)
    assert sched.request_steal(req.handle) == {"status": "finished"}
    assert req.state is RequestState.DONE and req.result(timeout=1) == tokens
    assert sched.stats()["counters"]["steals"] == 0
    sched.stop(drain=False)


# ------------------------------------------------------- peer prefix export --
def test_export_prefix_frames_full_trie_blocks(make_engine, llama_setup):
    """Donor side of the peer fetch: the published trie path comes back as a
    CRC'd v1 frame whose tokens are exactly the full-block prefix."""
    cfg, _, _ = llama_setup
    engine = make_engine()
    sched = ServingScheduler(
        engine, ServingConfig(prefix_cache=PrefixCacheConfig(enabled=True)),
        start=False)
    prompt = _prompt(40, cfg.vocab_size)  # 2 full blocks + a partial
    req = sched.submit(prompt, max_new_tokens=2)
    _run_until(sched, lambda: req.finished)

    chain = digest_chain(np.asarray(prompt, np.int32),
                         engine._state_manager.kv_block_size)
    assert len(chain) == 2
    payload = sched.export_prefix(chain)
    header, kv = handoff.unpack(payload)
    assert header["tokens"] == prompt[:32] and header["seen_tokens"] == 32
    assert kv.shape[2] == 2  # two full blocks, nothing partial
    assert header["extra"] == {"kind": "prefix"}
    # the truncated-hex catalog the probe doc publishes names the same chain
    catalog = sched.prefix_digest_catalog()
    assert chain[-1].hex()[:16] in catalog
    # asking deeper than the trie holds is a clean None, not a short frame
    assert sched.export_prefix(chain, min_blocks=3) is None
    sched.stop(drain=False)
