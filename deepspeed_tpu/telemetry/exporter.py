"""Stdlib-only HTTP exporter.

Serves the registry and span recorder to operators:

- ``GET /metrics``  → Prometheus text exposition (scrape target)
- ``GET /healthz``  → 200 ``{"status": "ok"}`` (liveness probe)
- ``GET /trace``    → Chrome-trace JSON of the recorded spans
- ``GET /flight``   → trigger a flight-recorder dump, return its JSON + path
  (404 unless ``telemetry.flight_recorder.enabled``)

Runs a daemon ``ThreadingHTTPServer``; ``port=0`` binds an ephemeral port
(the bound address is on ``.address`` after ``start()``).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from deepspeed_tpu.utils.logging import logger


class TelemetryHTTPServer:

    def __init__(self, registry, spans=None, host="127.0.0.1", port=0):
        self._registry = registry
        self._spans = spans
        self._host = host
        self._port = port
        self._server = None
        self._thread = None

    @property
    def address(self):
        """(host, port) once started."""
        return self._server.server_address if self._server else None

    @property
    def url(self):
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self):
        registry, spans = self._registry, self._spans

        class Handler(BaseHTTPRequestHandler):

            def _send(self, code, body, content_type):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    self._send(200, registry.render_prometheus(),
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    self._send(200, json.dumps({"status": "ok"}), "application/json")
                elif path == "/trace" and spans is not None:
                    self._send(200, json.dumps(spans.chrome_trace()), "application/json")
                elif path == "/flight":
                    from deepspeed_tpu import telemetry
                    recorder = telemetry.get_flight_recorder()
                    if recorder is None:
                        self._send(404, json.dumps(
                            {"error": "flight recorder not enabled "
                                      "(telemetry.flight_recorder.enabled)"}),
                                   "application/json")
                    else:
                        dump_path, doc = recorder.dump("http", return_doc=True)
                        self._send(200, json.dumps({"path": dump_path,
                                                    "dump": doc}, default=str),
                                   "application/json")
                else:
                    self._send(404, json.dumps({"error": f"no route {path}"}),
                               "application/json")

            def log_message(self, fmt, *args):
                ...  # scrapes must not spam the training log

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="dstpu-telemetry-http", daemon=True)
        self._thread.start()
        logger.info(f"telemetry: serving /metrics /healthz /trace on {self.url}")
        return self

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None


def start_http_server(registry, spans=None, host="127.0.0.1", port=0):
    return TelemetryHTTPServer(registry, spans=spans, host=host, port=port).start()


def scrape_metrics(url, timeout=5.0):
    """GET ``url`` (a /metrics endpoint or a bare host:port) and return the
    parsed families — the ``dstpu_report --metrics-url`` backend."""
    import urllib.request

    from deepspeed_tpu.telemetry.registry import parse_prometheus_text

    if not url.startswith(("http://", "https://")):
        url = "http://" + url
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        text = resp.read().decode()
    return parse_prometheus_text(text)
