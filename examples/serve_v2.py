"""Quickstart: FastGen-style ragged serving with the v2 engine.

Prefill + on-device decode_loop + continuous-batching generate + the
inference-checkpoint round-trip, on a tiny random llama.

Run:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/serve_v2.py

Server mode (``DSTPU_SERVE_MODE=server``): start the persistent serving layer
— ServingScheduler + ServingServer on an ephemeral port — submit two
overlapping SSE streaming requests over HTTP, and print tokens as they
arrive; then drain gracefully.

Fleet mode (``DSTPU_SERVE_MODE=fleet``): a disaggregated 4-replica fleet — two
prefill-role and two decode-role in-process replicas behind the FleetRouter.
Each request prefills (plus first token) on a prefill replica, hands its KV
off as a portable payload, and finishes decoding on a decode replica; the
final SSE event shows both legs. Then a fleet-wide graceful drain.

Supervised mode (``DSTPU_SERVE_MODE=supervised``): the fault-tolerance loop —
a ReplicaSupervisor owns two replica slots (readiness-gated registration),
one replica is killed mid-fleet, the supervisor detects the death and
restarts it automatically (visible as ``fleet_restarts_total`` and in the
``/v1/fleet/stats`` supervisor table), and requests keep flowing throughout
because the router's failover + circuit breaker route around the hole.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.realpath(__file__))))
import tempfile

if "host_platform_device_count" in os.environ.get("XLA_FLAGS", "") \
        or os.environ.get("JAX_PLATFORMS", "") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from deepspeed_tpu.models.llama import LlamaConfig, init_params
from deepspeed_tpu.inference.v2.config_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.engine_factory import (build_engine, build_hf_engine,
                                                       generate)
from deepspeed_tpu.inference.v2.ragged.manager_configs import (AllocationMode,
                                                               DSStateManagerConfig,
                                                               MemoryConfig)


def serve_main():
    """Persistent-server demo: overlapping streaming requests over HTTP, each
    traced end-to-end (X-DSTPU-Trace-Id), plus a flight-recorder dump and a
    per-request timeline report from the exported Chrome trace."""
    import json
    import threading
    import urllib.request

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.serving import ServingConfig, ServingScheduler, ServingServer

    trace_dir = tempfile.mkdtemp()
    telemetry.configure(telemetry.TelemetryConfig(
        enabled=True,
        trace_path=os.path.join(trace_dir, "serve.trace.json"),
        flight_recorder={"enabled": True, "dir": os.path.join(trace_dir, "flight"),
                         "watchdog_enabled": False}))

    cfg = LlamaConfig.tiny(vocab_size=512, max_position_embeddings=128)
    _, params = init_params(cfg, seq_len=16)
    engine_config = RaggedInferenceEngineConfig(
        state_manager=DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=128),
            max_context=128, max_ragged_batch_size=256, max_ragged_sequence_count=8),
        kv_block_size=16)
    engine = build_engine(params, cfg, engine_config)
    scheduler = ServingScheduler(engine, ServingConfig(decode_chunk=4))
    server = ServingServer(scheduler).start()
    print(f"serving on {server.url}")

    def stream_one(name, prompt, n):
        body = json.dumps({"prompt": prompt, "max_new_tokens": n,
                           "stream": True}).encode()
        req = urllib.request.Request(server.url + "/v1/generate", data=body,
                                     headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            trace_id = resp.headers["X-DSTPU-Trace-Id"]
            for line in resp:
                line = line.decode().strip()
                if not line.startswith("data: "):
                    continue
                event = json.loads(line[len("data: "):])
                if event.get("done"):
                    assert event["trace_id"] == trace_id
                    print(f"[{name}] done: state={event['state']} "
                          f"trace={trace_id} tokens={event['tokens']}")
                else:
                    print(f"[{name}] token {event['index']}: {event['token']}")

    rng = np.random.default_rng(0)
    threads = [threading.Thread(target=stream_one,
                                args=(name, rng.integers(0, cfg.vocab_size, n).tolist(), 8))
               for name, n in (("A", 24), ("B", 9))]
    for t in threads:
        t.start()  # both requests are in flight concurrently
    for t in threads:
        t.join()

    stats = json.loads(urllib.request.urlopen(server.url + "/v1/stats",
                                              timeout=10).read())
    assert stats["counters"]["completed"] == 2, stats
    assert stats["latency"]["ttft_s"]["p50"] is not None, stats

    # black-box dump on demand (same payload a SIGUSR1 would produce)
    dump_path = telemetry.get_flight_recorder().dump("demo")
    with open(dump_path) as f:
        dump = json.load(f)
    assert dump["metrics"]["serving_completions_total"][0][1] == 2
    print(f"flight dump: {dump_path}")

    server.stop()  # graceful drain
    assert engine.free_blocks == 128, "KV blocks must all return to the pool"
    engine.close()

    telemetry.shutdown()  # writes trace_path
    from deepspeed_tpu.env_report import trace_report
    assert trace_report(os.path.join(trace_dir, "serve.trace.json")) == 0
    print("OK")


def fleet_main():
    """Disaggregated-fleet demo: 2 prefill + 2 decode in-process replicas
    behind the router; each request's KV hands off between pools mid-request
    and the final event carries the per-leg replica attribution."""
    import json
    import threading
    import urllib.request

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.fleet import FleetRouter, ReplicaManager
    from deepspeed_tpu.serving import ServingConfig

    telemetry.configure(telemetry.TelemetryConfig(enabled=True))

    cfg = LlamaConfig.tiny(vocab_size=512, max_position_embeddings=128)
    _, params = init_params(cfg, seq_len=16)
    engine_config = RaggedInferenceEngineConfig(
        state_manager=DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=128),
            max_context=128, max_ragged_batch_size=256, max_ragged_sequence_count=8),
        kv_block_size=16)

    manager = ReplicaManager(engine_factory=lambda: build_engine(params, cfg, engine_config),
                             serving_config=ServingConfig(decode_chunk=4))
    for _ in range(2):
        manager.add_local(role="prefill")
        manager.add_local(role="decode")
    router = FleetRouter(manager).start()
    print(f"fleet router on {router.url} (pools: "
          f"{manager.pool_size('prefill')} prefill, {manager.pool_size('decode')} decode)")

    def stream_one(name, prompt, n):
        body = json.dumps({"prompt": prompt, "max_new_tokens": n,
                           "stream": True, "session": name}).encode()
        req = urllib.request.Request(router.url + "/v1/generate", data=body,
                                     headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            trace_id = resp.headers["X-DSTPU-Trace-Id"]
            for line in resp:
                line = line.decode().strip()
                if not line.startswith("data: "):
                    continue
                event = json.loads(line[len("data: "):])
                if event.get("done"):
                    legs = [(leg["kind"], leg["replica"]) for leg in event["legs"]]
                    assert [k for k, _ in legs] == ["prefill", "decode"], legs
                    assert event["trace_id"] == trace_id
                    print(f"[{name}] done: state={event['state']} legs={legs} "
                          f"tokens={event['tokens']}")
                else:
                    print(f"[{name}] token {event['index']}: {event['token']}")

    rng = np.random.default_rng(0)
    threads = [threading.Thread(target=stream_one,
                                args=(name, rng.integers(0, cfg.vocab_size, n).tolist(), 8))
               for name, n in (("A", 24), ("B", 9))]
    for t in threads:
        t.start()  # both requests cross the prefill->decode boundary concurrently
    for t in threads:
        t.join()

    stats = json.loads(urllib.request.urlopen(router.url + "/v1/fleet/stats",
                                              timeout=10).read())
    assert stats["roles"] == {"prefill": 2, "decode": 2}, stats
    dispatches = {row["id"]: row["dispatches"] for row in stats["replicas"]}
    assert sum(dispatches.values()) >= 4, dispatches  # 2 requests x 2 legs
    print(f"per-replica dispatches: {dispatches}")

    router.stop()  # fleet-wide graceful drain (schedulers stopped, engines closed)
    telemetry.shutdown()
    print("OK")


def supervised_main():
    """Fault-tolerance demo: a supervised 2-replica fleet survives a replica
    kill — the supervisor readiness-gates registration, detects the death,
    restarts the replica with backoff, and the router serves through it all
    (failover during the outage, full capacity after the restart)."""
    import json
    import time
    import urllib.request

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.fleet import (FleetConfig, FleetRouter, ReplicaManager,
                                     SlotState, SupervisorConfig)
    from deepspeed_tpu.fleet.supervisor import ReplicaSupervisor
    from deepspeed_tpu.serving import ServingConfig

    telemetry.configure(telemetry.TelemetryConfig(enabled=True))

    cfg = LlamaConfig.tiny(vocab_size=512, max_position_embeddings=128)
    _, params = init_params(cfg, seq_len=16)
    engine_config = RaggedInferenceEngineConfig(
        state_manager=DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=128),
            max_context=128, max_ragged_batch_size=256, max_ragged_sequence_count=8),
        kv_block_size=16)

    manager = ReplicaManager(
        engine_factory=lambda: build_engine(params, cfg, engine_config),
        config=FleetConfig(probe_ttl_s=0.0),
        serving_config=ServingConfig(decode_chunk=4))
    supervisor = ReplicaSupervisor(manager, SupervisorConfig(
        poll_interval_s=0.05, restart_backoff_base_s=0.1,
        restart_backoff_cap_s=0.5, max_crashes=5, crash_window_s=120.0))
    slot_a = supervisor.add_local(role="mixed")
    supervisor.add_local(role="mixed")
    supervisor.start()
    assert supervisor.wait_ready(timeout=300), "replicas never became ready"
    router = FleetRouter(manager).start()
    print(f"supervised fleet on {router.url}: "
          f"{manager.pool_size('mixed')} replicas "
          f"(registration was gated on /healthz readiness)")

    def generate(name):
        body = json.dumps({"prompt": rng.integers(0, cfg.vocab_size, 12).tolist(),
                           "max_new_tokens": 6}).encode()
        req = urllib.request.Request(router.url + "/v1/generate", data=body,
                                     headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            doc = json.loads(resp.read())
        assert doc["state"] == "DONE", doc
        print(f"[{name}] done: state={doc['state']} "
              f"replica={doc['legs'][0]['replica']} tokens={doc['tokens']}")

    rng = np.random.default_rng(0)
    generate("before-kill")

    # a replica dies abruptly (what a SIGKILL'd process looks like in-process)
    slot_a.replica.kill("demo crash")
    print(f"killed replica {slot_a.id}; serving continues on the survivor...")
    generate("during-outage")  # failover + breaker route around the hole

    deadline = time.monotonic() + 300
    while not (slot_a.state is SlotState.READY and slot_a.restarts >= 1):
        assert time.monotonic() < deadline, "supervisor never restarted the replica"
        time.sleep(0.05)
    print(f"supervisor restarted {slot_a.id} automatically "
          f"(restarts={slot_a.restarts})")
    generate("after-restart")

    stats = json.loads(urllib.request.urlopen(
        router.url + "/v1/fleet/stats", timeout=10).read())
    sup = stats["supervisor"]
    assert sup["restarts"] >= 1, sup
    assert all(s["state"] == "READY" for s in sup["slots"]), sup
    assert manager.pool_size("mixed") == 2
    restarts_metric = telemetry.get_registry().snapshot()["fleet_restarts_total"]
    assert restarts_metric[0][1] >= 1
    print(f"supervisor table: restarts={sup['restarts']} "
          f"slots={[(s['id'], s['state']) for s in sup['slots']]}")

    supervisor.stop()
    router.stop()  # graceful fleet-wide drain
    telemetry.shutdown()
    print("OK")


def main():
    cfg = LlamaConfig.tiny(vocab_size=512, max_position_embeddings=128)
    _, params = init_params(cfg, seq_len=16)
    engine_config = RaggedInferenceEngineConfig(
        state_manager=DSStateManagerConfig(
            memory_config=MemoryConfig(mode=AllocationMode.ALLOCATE, size=128),
            max_context=128, max_ragged_batch_size=256, max_ragged_sequence_count=8),
        kv_block_size=16,
        # int4 at-rest weights (ZeRO-Inference): halve again with bits=4
        weight_quantization={"enabled": True, "bits": 8},
        # serving telemetry: batch/token/KV gauges on a scrapeable endpoint
        # (ephemeral port; curl <metrics_url> or bin/dstpu_report --metrics-url)
        telemetry={"enabled": True, "http": {"enabled": True, "port": 0}})
    engine = build_engine(params, cfg, engine_config)
    print(f"metrics endpoint: {engine.metrics_url}")

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, n) for n in (24, 9, 40)]

    # continuous batching, chunks of 4 decode steps per device dispatch
    outs = generate(engine, prompts, max_new_tokens=12, decode_chunk=4)
    for i, out in enumerate(outs):
        print(f"seq {i}: {len(prompts[i])} prompt tokens -> {out}")

    # KV offload: evict a cold sequence; it restores transparently on touch
    pre = engine.put([7], [prompts[0]])
    engine.offload_sequence(7)
    first = np.asarray([int(np.argmax(np.asarray(pre)[0]))], np.int32)
    toks = engine.decode_loop([7], [first], 4)   # restore + 4 steps, one program
    print("offload/restore decode:", np.asarray(toks)[0].tolist())

    # inference-checkpoint round-trip
    d = tempfile.mkdtemp()
    engine.serialize(d)
    from deepspeed_tpu.telemetry import TelemetryConfig
    rebuilt = build_hf_engine(  # auto-detects the DS checkpoint; keep the one
        d, engine_config.model_copy(update={"telemetry": TelemetryConfig()}))
    np.testing.assert_allclose(np.asarray(rebuilt.put([0], [prompts[1]])),
                               np.asarray(engine.put([9], [prompts[1]])),
                               rtol=1e-4, atol=1e-4)
    print("serialize round-trip OK")
    import urllib.request
    with urllib.request.urlopen(engine.metrics_url, timeout=5) as resp:
        body = resp.read().decode()
    assert "inference_batches_total" in body and "inference_tokens_total" in body
    print("metrics scrape OK")
    engine.close()
    print("OK")


if __name__ == "__main__":
    mode = os.environ.get("DSTPU_SERVE_MODE")
    if mode == "server":
        serve_main()
    elif mode == "fleet":
        fleet_main()
    elif mode == "supervised":
        supervised_main()
    else:
        main()
