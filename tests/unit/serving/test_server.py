"""ServingServer HTTP front-end: JSON + SSE wire formats, backpressure status
codes, client-disconnect cancellation, stats, and graceful drain."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu.serving import (RequestState, ServingConfig, ServingScheduler,
                                   ServingServer)


def _post(url, doc, timeout=120):
    req = urllib.request.Request(url + "/v1/generate", data=json.dumps(doc).encode(),
                                 headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def _sse_events(resp):
    events = []
    for line in resp:
        line = line.decode().strip()
        if line.startswith("data: "):
            events.append(json.loads(line[len("data: "):]))
    return events


@pytest.fixture
def server(make_engine):
    engine = make_engine()
    srv = ServingServer(ServingScheduler(engine, ServingConfig())).start()
    yield srv, engine
    srv.stop(drain=False)


def test_generate_json_roundtrip_and_stats(server, llama_setup):
    cfg, _, _ = llama_setup
    srv, engine = server
    prompt = (np.arange(7) % cfg.vocab_size).tolist()
    with _post(srv.url, {"prompt": prompt, "max_new_tokens": 5}) as resp:
        doc = json.loads(resp.read())
    assert resp.status == 200
    assert doc["state"] == "DONE" and doc["finish_reason"] == "length"
    assert len(doc["tokens"]) == doc["n_tokens"] == 5
    assert doc["ttft_s"] is not None and doc["ttft_s"] <= doc["e2e_s"]

    stats = json.loads(urllib.request.urlopen(srv.url + "/v1/stats", timeout=10).read())
    assert stats["counters"]["completed"] == 1
    assert stats["engine"]["tracked_sequences"] == 0

    health = json.loads(urllib.request.urlopen(srv.url + "/healthz", timeout=10).read())
    assert health == {"status": "ok"}


def test_generate_sse_stream_matches_blocking(server, llama_setup):
    cfg, _, _ = llama_setup
    srv, _ = server
    prompt = (np.arange(11) % cfg.vocab_size).tolist()
    with _post(srv.url, {"prompt": prompt, "max_new_tokens": 6, "stream": True}) as resp:
        assert resp.headers["Content-Type"] == "text/event-stream"
        events = _sse_events(resp)
    *tokens, final = events
    assert [e["index"] for e in tokens] == list(range(6))
    assert final["done"] is True and final["state"] == "DONE"
    assert [e["token"] for e in tokens] == final["tokens"]

    with _post(srv.url, {"prompt": prompt, "max_new_tokens": 6}) as resp:
        blocking = json.loads(resp.read())
    assert blocking["tokens"] == final["tokens"]  # same greedy continuation


def test_bad_requests_get_400(server):
    srv, _ = server
    for body in ({}, {"prompt": []}, {"prompt": "text"}, {"prompt": [1, "x"]},
                 {"prompt": [1], "max_new_tokens": 0},
                 {"prompt": [1], "temperature": "hot"},
                 {"prompt": [1], "max_new_tokens": "x"}):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url, body)
        assert e.value.code == 400, body
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(srv.url + "/v1/nope", data=b"{}", timeout=10)
    assert e.value.code == 404


def test_queue_full_returns_429_in_reject_mode(make_engine):
    engine = make_engine()
    # start=False: nothing drains the queue, so capacity is hit deterministically
    sched = ServingScheduler(engine, ServingConfig(queue_capacity=1), start=False)
    srv = ServingServer(sched).start()
    try:
        results = {}

        def first():
            try:
                with _post(srv.url, {"prompt": [1, 2]}) as resp:
                    results["first"] = json.loads(resp.read())
            except Exception as e:  # cancelled at shutdown is fine too
                results["first"] = e

        t = threading.Thread(target=first, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while sched.queue_depth < 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url, {"prompt": [3, 4]})
        assert e.value.code == 429
        assert json.loads(e.value.read())["queue_depth"] == 1
    finally:
        srv.stop(drain=False)  # cancels the queued request; its handler returns
    t.join(timeout=10)
    assert not t.is_alive()


def test_draining_server_returns_503(server):
    srv, _ = server
    srv._draining.set()  # what stop() flips first, observed before teardown
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv.url, {"prompt": [1, 2]})
    assert e.value.code == 503
    health = json.loads(urllib.request.urlopen(srv.url + "/healthz", timeout=10).read())
    assert health == {"status": "draining"}


def test_client_disconnect_cancels_request_and_frees_kv(server, llama_setup):
    cfg, _, _ = llama_setup
    srv, engine = server
    free0 = engine.free_blocks
    prompt = (np.arange(10) % cfg.vocab_size).tolist()
    resp = _post(srv.url, {"prompt": prompt, "max_new_tokens": 100000, "stream": True})
    # read one real token, then hang up mid-generation
    for line in resp:
        if line.decode().strip().startswith("data: "):
            break
    sock = resp.fp.raw._sock if hasattr(resp.fp, "raw") else None
    resp.close()
    if sock is not None:  # make the FIN unambiguous for the handler thread
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
    deadline = time.monotonic() + 60
    sched = srv.scheduler
    while sched.stats()["counters"]["cancelled"] < 1:
        assert time.monotonic() < deadline, "disconnect did not cancel the request"
        time.sleep(0.01)
    while engine.free_blocks != free0:
        assert time.monotonic() < deadline, "KV blocks not returned after cancel"
        time.sleep(0.01)
    assert engine._state_manager.n_tracked_sequences == 0


def test_graceful_drain_finishes_in_flight(make_engine, llama_setup):
    cfg, _, _ = llama_setup
    engine = make_engine()
    sched = ServingScheduler(engine, ServingConfig(drain_timeout_s=120))
    srv = ServingServer(sched).start()
    prompt = (np.arange(6) % cfg.vocab_size).tolist()
    req = sched.submit(prompt, max_new_tokens=4)
    url = srv.url
    srv.stop(drain=True)  # stop admitting, finish in-flight, then close
    assert req.state is RequestState.DONE and len(req.tokens) == 4
    assert engine._state_manager.n_tracked_sequences == 0
    with pytest.raises(OSError):  # listener is really down
        urllib.request.urlopen(url + "/healthz", timeout=1)


def test_healthz_reflects_readiness_not_just_liveness(make_engine):
    """The fleet supervisor's registration gate: /healthz answers 'ok' only
    once the scheduler loop ticks, and stops saying 'ok' when the scheduler
    is dead even though the listener still answers."""
    engine = make_engine()
    scheduler = ServingScheduler(engine, ServingConfig())
    srv = ServingServer(scheduler).start()
    try:
        deadline = time.monotonic() + 30
        while True:
            health = json.loads(urllib.request.urlopen(
                srv.url + "/healthz", timeout=10).read())
            if health == {"status": "ok"}:
                break
            assert health == {"status": "starting"}
            assert time.monotonic() < deadline, "never became ready"
        scheduler.kill("test")
        health = json.loads(urllib.request.urlopen(
            srv.url + "/healthz", timeout=10).read())
        assert health["status"] != "ok"  # a dead scheduler is not dispatchable
    finally:
        srv._draining.set()
        srv._server.shutdown()
        srv._server.server_close()
        srv._server = None
