"""BERT encoder (bidirectional) for the v1 injection-container family.

Reference exercises BERT through ``deepspeed/module_inject/containers/bert.py``
(HFBertLayerPolicy); here it is a native flax encoder whose parameter layout
the container policy (``module_inject/containers.py``) maps HF checkpoints
into. Faithful to ``transformers.BertModel``: post-LN residuals, exact-erf
gelu, eps 1e-12, learned absolute positions + token-type embeddings, tanh
pooler over [CLS].
"""

from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    # distilbert deltas: no token-type embeddings, no [CLS] pooler
    use_token_type: bool = True
    use_pooler: bool = True
    dtype: any = jnp.float32

    @classmethod
    def tiny(cls, **kw):
        base = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=64)
        base.update(kw)
        return cls(**base)


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask):
        cfg = self.cfg
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        dense = partial(nn.Dense, dtype=cfg.dtype)
        q = dense(cfg.hidden_size, name="query")(x).reshape(*x.shape[:-1], H, D)
        k = dense(cfg.hidden_size, name="key")(x).reshape(*x.shape[:-1], H, D)
        v = dense(cfg.hidden_size, name="value")(x).reshape(*x.shape[:-1], H, D)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(D)
        if attention_mask is not None:
            logits = jnp.where(attention_mask[:, None, None, :] > 0, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return out.reshape(*x.shape[:-1], cfg.hidden_size)


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask):
        cfg = self.cfg
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_eps, dtype=cfg.dtype)
        dense = partial(nn.Dense, dtype=cfg.dtype)
        attn = BertSelfAttention(cfg, name="attention")(x, attention_mask)
        attn = dense(cfg.hidden_size, name="attention_output")(attn)
        x = ln(name="attention_layernorm")(x + attn)          # post-LN
        h = nn.gelu(dense(cfg.intermediate_size, name="intermediate")(x),
                    approximate=False)
        h = dense(cfg.hidden_size, name="output")(h)
        return ln(name="output_layernorm")(x + h)


class BertModel(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="word_embeddings")(input_ids)
        x = x + nn.Embed(cfg.max_position_embeddings, cfg.hidden_size, dtype=cfg.dtype,
                         name="position_embeddings")(jnp.arange(S)[None])
        if cfg.use_token_type:
            x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                             name="token_type_embeddings")(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="embeddings_layernorm")(x)
        for i in range(cfg.num_hidden_layers):
            x = BertLayer(cfg, name=f"layer_{i}")(x, attention_mask)
        if not cfg.use_pooler:
            return x, None
        pooled = nn.tanh(nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="pooler")(x[:, 0]))
        return x, pooled


def init_params(cfg: BertConfig, batch_size: int = 2, seq_len: Optional[int] = None,
                rng=None):
    model = BertModel(cfg)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    S = seq_len or min(cfg.max_position_embeddings, 16)
    ids = jnp.zeros((batch_size, S), jnp.int32)
    return model, model.init(rng, ids)["params"]
