"""Fleet config blocks.

The fleet layer runs N ``(InferenceEngineV2 + ServingScheduler +
ServingServer)`` replicas behind one router; these knobs size the router's
dispatch behavior and the autoscaler's policy loop. Validated pydantic-style
like the other config blocks (``serving/config.py``, ``telemetry/config.py``).
"""

from typing import Literal, Optional, Tuple

from pydantic import Field

from deepspeed_tpu.fleet.breaker import BreakerConfig
from deepspeed_tpu.fleet.faults import FaultConfig
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel
from deepspeed_tpu.serving.config import (DEFAULT_MAX_RESUME_BODY_BYTES,
                                          OverloadConfig, PrefixCacheConfig,
                                          SpeculativeConfig)

ReplicaRole = Literal["mixed", "prefill", "decode"]
"""``mixed`` serves whole requests; ``prefill``/``decode`` replicas form the
disaggregated pools — a request prefills (plus first token) on a prefill-role
replica, then its KV hands off to a decode-role replica for the rest."""


class GlobalQueueConfig(DeepSpeedConfigModel):
    """Router global queue (``fleet/global_queue.py``): queued work lives at
    the router in priority/deadline order and replicas pull it when they have
    a free dispatch slot (ROADMAP 3c, first half)."""

    enabled: bool = True
    """False = the pre-queue blind least-loaded push dispatch (the control
    arm the overload gates compare against)."""

    capacity: int = Field(256, ge=1)
    """Queue entries beyond which admission answers 429 + ``Retry-After``."""

    max_inflight_per_replica: int = Field(32, ge=1)
    """Concurrently granted legs per replica (continuous batching happily
    runs several; the cap keeps a burst from piling onto one replica)."""

    acquire_timeout_s: float = Field(30.0, gt=0)
    """Queue-wait bound for requests without a deadline (deadline'd requests
    expire at their own deadline, whichever is sooner)."""

    retry_after_floor_s: float = Field(0.5, gt=0)
    retry_after_cap_s: float = Field(30.0, gt=0)
    """Bounds on the grant-rate-derived ``Retry-After`` estimate."""


class HedgeConfig(DeepSpeedConfigModel):
    """Hedged dispatch (``fleet/router.py``): a request whose next token
    hasn't arrived within the TTFT budget — before the first token OR
    mid-stream (greedy/seeded legs are token-identical, so a hedge can
    replay and skip the already-streamed prefix) — is dispatched again on a
    second replica; the first leg past the prefix wins, the loser is
    cancelled (KV freed). Off by default — hedging doubles worst-case
    dispatch cost by design."""

    enabled: bool = False

    ttft_budget_s: Optional[float] = Field(None, gt=0)
    """Fixed TTFT budget before hedging; None = derive from the router's
    observed TTFT p95 (``budget_factor`` × p95)."""

    budget_factor: float = Field(1.5, gt=0)
    """Multiplier on the observed TTFT p95 when deriving the budget."""

    min_samples: int = Field(8, ge=1)
    """TTFT samples before the p95 derivation is trusted;
    ``default_budget_s`` applies until then."""

    default_budget_s: float = Field(1.0, gt=0)
    """Cold-start TTFT budget."""

    deadline_frac: float = Field(0.5, gt=0, le=1)
    """Cap the per-token hedge wait at this fraction of the request's
    remaining deadline (deadline'd requests only): a cold-start default
    budget must not eat the whole deadline before a hedge can still win."""

    min_budget_s: float = Field(0.25, gt=0)
    """Floor under the p95-derived budget: a lightly-loaded fleet's tiny
    TTFT p95 must not arm a hair-trigger the first burst then trips (and
    the floor bounds how often a waiting request wakes to re-evaluate)."""

    max_hedge_frac: float = Field(0.1, ge=0, le=1)
    """Storm brake: speculative hedges (budget expired but the slow replica
    is NOT demotion-grade slow vs its peers) are token-bucket limited to
    this fraction of admitted requests. Evidence-driven hedges — the
    current replica's TTFT EWMA is demotion-grade — bypass the brake:
    fleet-wide contention inflates every EWMA together and never looks
    like evidence, so a storm cannot feed itself; a single stalled
    replica does, so its victims are always rescued."""

    interactive_only: bool = True
    """Hedge only interactive-class requests (batch latency is nobody's
    p99); False hedges everything eligible."""

    slow_demote_factor: float = Field(3.0, gt=1)
    """A replica whose TTFT EWMA exceeds this multiple of the candidate
    median is demoted — picked only when nothing faster has capacity. The
    latency-shaped sibling of the failure-shaped circuit breaker."""


class CacheRouteConfig(DeepSpeedConfigModel):
    """Cache-aware placement (``fleet/router.py``): replicas publish a digest
    catalog of their prefix-cache trie in the probe doc; the router hashes
    the request's block-aligned prefix chain at admission and dispatches to
    the replica holding the longest cached prefix. Staleness is bounded by
    ``FleetConfig.probe_ttl_s`` — a stale hint costs one misrouted dispatch
    that then misses locally, never correctness."""

    enabled: bool = True
    """False = ignore published digests (rendezvous/least-loaded only; the
    hash-routing control arm of the routing A/B gate)."""

    peer_fetch: bool = True
    """On a local trie miss where a peer's catalog matches the request chain
    deeper, fetch those KV blocks from the peer over the handoff frame
    instead of recomputing them (``POST /v1/prefix/export``). CRC-covered:
    a corrupt frame is rejected loudly and the prefill recomputes cold."""

    fetch_timeout_s: float = Field(2.0, gt=0)
    """Budget for one peer prefix fetch. Deliberately short: two in-process
    replicas fetching from each other symmetrically would block both
    scheduler loops; timing out degrades both sides to a cold prefill."""

    min_match_blocks: int = Field(1, ge=1)
    """Smallest digest-chain match (in blocks) that steers placement or
    justifies a peer fetch; shorter matches are noise."""


class StealConfig(DeepSpeedConfigModel):
    """Cross-replica work stealing (``fleet/router.py``): a request that has
    produced no token within the wait budget on a hot replica — still queued,
    or early in decode — is claimed back (``POST /v1/steal``), exported
    token-identically when mid-decode, and re-dispatched to a colder replica.
    The hedged-dispatch shape (PR 14) moving work instead of duplicating it.
    Off by default: stealing adds a dispatch round-trip by design."""

    enabled: bool = False

    wait_budget_s: float = Field(0.5, gt=0)
    """No-first-token budget before the router considers stealing the leg."""

    min_deadline_headroom_s: float = Field(2.0, ge=0)
    """Only steal a request whose remaining deadline exceeds this (or that
    carries no deadline): a steal costs a round-trip plus a re-dispatch, so
    tight-deadline requests are left to the hedging machinery."""

    load_ratio: float = Field(2.0, gt=1)
    """The victim's probe load must exceed the target's by this factor for
    the move to count as hot→cold; symmetric load never triggers a steal."""


class ParkConfig(DeepSpeedConfigModel):
    """Fleet-parked sessions (``fleet/park_store.py``): a finished-but-
    continuable session's KV exports as a v2 park frame at the replica and
    banks at the router under its session key; the session's next turn — a
    generate whose prompt strictly extends the parked history — rehydrates on
    ANY replica via an internal resume-with-prompt leg, prefilling only the
    new suffix. The serving-layer end of the same ladder is
    ``ServingConfig.kv_tiers`` (device→host→disk); parking is the fleet-global
    fourth rung. Off by default: parking exports KV at every session finish."""

    enabled: bool = False

    max_sessions: int = Field(256, ge=1)
    """Parked-session cap; beyond it the coldest (LRU) session drops."""

    max_bytes: int = Field(256 << 20, ge=1)
    """Byte budget across all parked frames (a park frame is a KV-block dump:
    kilobytes for a test model, hundreds of megabytes for a real one)."""

    ttl_s: float = Field(600.0, ge=0)
    """Seconds a parked session survives untouched; 0 = no expiry. A dropped
    park costs the returning turn a cold prefill, never correctness."""


class AutoscaleConfig(DeepSpeedConfigModel):
    """Policy knobs for :class:`deepspeed_tpu.fleet.policy.FleetAutoscaler`."""

    enabled: bool = False
    """Run the policy loop (``FleetAutoscaler.start()``); disabled = manual
    ``step()`` only (tests, external control loops)."""

    interval_s: float = Field(1.0, gt=0)
    """Seconds between policy observations."""

    min_replicas: int = Field(1, ge=1)
    """Never drain below this many replicas (per managed role)."""

    max_replicas: int = Field(8, ge=1)
    """Never grow beyond this many replicas (per managed role)."""

    role: ReplicaRole = "mixed"
    """Which pool the autoscaler grows and shrinks (one autoscaler per role;
    run several for disaggregated fleets)."""

    scale_up_queue_depth: float = Field(4.0, ge=0)
    """Mean queued-requests-per-replica above which the pool is considered
    saturated."""

    scale_up_kv_pressure: float = Field(0.9, ge=0, le=1)
    """Mean KV-pool occupancy (1 - free/capacity) above which the pool is
    considered saturated."""

    sustain_ticks: int = Field(3, ge=1)
    """Consecutive saturated observations before a scale-up fires (guards
    against reacting to a transient burst)."""

    scale_down_idle_ticks: int = Field(10, ge=1)
    """Consecutive fully-idle observations (zero queued, zero in-flight,
    pressure below the threshold) before one replica is drained."""

    slo_scale_up: bool = False
    """Treat an open SLO breach episode (``telemetry.slo`` engine,
    fast+slow burn over threshold) as a saturated observation — and veto
    scale-down while it is open. Requires an active telemetry session with
    SLOs configured; off by default."""


class SupervisorConfig(DeepSpeedConfigModel):
    """Knobs for :class:`deepspeed_tpu.fleet.supervisor.ReplicaSupervisor`."""

    poll_interval_s: float = Field(0.25, gt=0)
    """Monitor-loop cadence: exit/hang checks and restart scheduling."""

    ready_timeout_s: float = Field(120.0, gt=0)
    """How long a freshly-spawned replica gets to answer a healthy probe
    before the launch counts as a crash (registration is gated on readiness —
    an unready replica is never dispatched to)."""

    probe_hang_failures: int = Field(4, ge=1)
    """Consecutive failed liveness probes of a READY replica before it is
    declared hung, killed, and restarted (exits are detected immediately;
    this catches the wedged-but-alive case)."""

    restart_backoff_base_s: float = Field(0.5, ge=0)
    restart_backoff_multiplier: float = Field(2.0, ge=1)
    restart_backoff_cap_s: float = Field(30.0, gt=0)
    restart_jitter_frac: float = Field(0.1, ge=0, le=1)
    """Exponential restart backoff (shared ``breaker.backoff_delay`` formula):
    crash *k* in the crash window waits ``base * multiplier**(k-1)`` (capped,
    ± jitter) before respawning."""

    max_crashes: int = Field(3, ge=1)
    """Crash-loop budget: this many crashes within ``crash_window_s``
    quarantines the slot — no further respawns until ``reset()`` — instead of
    silently burning CPU on a persistent crasher forever."""

    crash_window_s: float = Field(60.0, gt=0)
    """Sliding window for the crash-loop budget (also the backoff exponent's
    memory: crashes aging out of the window reset the schedule)."""

    seed: int = 0
    """Restart-jitter determinism (chaos runs replay the same schedule)."""


class FleetConfig(DeepSpeedConfigModel):
    """Knobs for the replica manager + front-end router."""

    host: str = "127.0.0.1"
    port: int = Field(0, ge=0, le=65535)
    """Router bind address; port 0 = ephemeral (read ``router.url`` after
    ``start()``)."""

    affinity_header: str = "X-DSTPU-Session"
    """Request header (or JSON ``session`` field) carrying the session key for
    rendezvous-hash affinity; absent = least-loaded dispatch."""

    default_max_new_tokens: int = Field(64, ge=1)
    """Generation budget when the request doesn't say — the router must know
    the total to split a disaggregated request into prefill-plus-first-token
    and decode-the-rest legs (matches ``ServingConfig.default_max_new_tokens``
    so routed and direct requests behave alike)."""

    probe_ttl_s: float = Field(0.25, ge=0)
    """How long a replica's health/load probe is trusted before the router
    re-probes; 0 = probe on every dispatch (tests)."""

    request_timeout_s: float = Field(120.0, gt=0)
    """Whole-leg upstream budget (a replica that blocks longer fails over or
    errors the client request)."""

    connect_timeout_s: float = Field(5.0, gt=0)
    """Upstream TCP-connect budget, separate from the read budget: a
    black-holed upstream costs a dispatch thread this much, not the full
    ``request_timeout_s``."""

    read_timeout_s: float = Field(30.0, gt=0)
    """Per-read upstream budget (headers, and the gap between SSE events): a
    replica that stops producing bytes mid-leg dies as a
    :class:`~deepspeed_tpu.fleet.replica.ReplicaDied` — a breaker signal —
    within this bound."""

    max_attempts: int = Field(3, ge=1)
    """Dispatch attempts per request leg: a 503/429/connection error excludes
    the replica and retries on the next candidate, up to this bound (and never
    more than the pool size)."""

    retry_backoff_base_s: float = Field(0.02, ge=0)
    retry_backoff_cap_s: float = Field(0.5, gt=0)
    retry_jitter_frac: float = Field(0.25, ge=0, le=1)
    """Bounded-jitter backoff between failover attempts of one leg (the
    shared ``breaker.backoff_delay`` policy; 0 base = retry immediately —
    the deterministic test formulation). Failed *probes* reuse the same
    formula at probe scale: a replica whose probe raised is not re-probed
    before an exponentially-growing fraction of ``probe_backoff_cap_s``."""

    probe_backoff_cap_s: float = Field(10.0, gt=0)
    """Cap on the failed-probe re-probe backoff."""

    drain_timeout_s: float = Field(30.0, ge=0)
    """Per-replica graceful-drain budget (in-flight requests get this long to
    finish before being cancelled)."""

    max_resume_body_bytes: int = Field(DEFAULT_MAX_RESUME_BODY_BYTES, gt=0)
    """Upper bound on a client ``POST /v1/resume`` body at the router (the
    base64 KV-handoff payload; fully buffered per handler thread — see
    ``ServingConfig.max_resume_body_bytes``)."""

    prefix_cache: PrefixCacheConfig = PrefixCacheConfig()
    """Automatic prefix caching applied to fleet-built local replicas
    (``serving/config.PrefixCacheConfig``). When ``enabled``, this block is
    authoritative for the roles in ``prefix_cache_roles`` and the cache is
    forced OFF for the others — the disaggregated shape: the prefill pool
    reuses shared prompts, the decode pool (which only ever imports handed-off
    KV) carries no trie. Disabled (default) = replicas keep whatever their own
    ``ServingConfig.prefix_cache`` says."""

    prefix_cache_roles: Tuple[ReplicaRole, ...] = ("mixed", "prefill")
    """Replica roles that receive ``prefix_cache`` when it is enabled."""

    speculative: Optional[SpeculativeConfig] = None
    """Speculative decoding applied to fleet-built local replicas
    (``serving/config.SpeculativeConfig``). When set, this block is
    authoritative for the roles in ``speculative_roles`` and drafting is
    forced OFF for the others; None = replicas keep whatever their own
    ``ServingConfig.speculative`` says. Trie-backed drafting is a
    prefill/mixed-role concern (those pools carry the prefix-cache trie);
    decode-role replicas self-draft from the request's own history, with the
    acceptance EWMA riding the prefill→decode handoff payload so adaptation
    survives the migration. With ``drafter`` set to ``learned``/``auto`` the
    block's tree budgets and ``draft_head_path`` flow to the listed roles
    verbatim; the handoff additionally carries the per-drafter EWMAs and the
    draft-head id, and a recipient whose heads differ drops only the learned
    EWMA (re-explored cold) while keeping the rest of the drafter state."""

    speculative_roles: Tuple[ReplicaRole, ...] = ("mixed", "decode")
    """Replica roles that receive ``speculative`` when it is set. Prefill
    replicas are excluded by default — they generate exactly one token per
    request, so there is no decode loop to speed up."""

    global_queue: GlobalQueueConfig = GlobalQueueConfig()
    """Router global queue + pull dispatch (``fleet/global_queue.py``)."""

    hedge: HedgeConfig = HedgeConfig()
    """Hedged dispatch against slow-but-alive replicas."""

    cache_route: CacheRouteConfig = CacheRouteConfig()
    """Cache-aware placement over the replicas' published digest catalogs,
    plus cross-replica prefix-KV fetch; see :class:`CacheRouteConfig`."""

    steal: StealConfig = StealConfig()
    """Cross-replica work stealing; see :class:`StealConfig`."""

    park: ParkConfig = ParkConfig()
    """Fleet-parked sessions that rehydrate on any replica; see
    :class:`ParkConfig`."""

    kv_transport: Literal["binary", "base64"] = "binary"
    """Preferred resume/handoff wire transport toward HTTP replicas:
    ``binary`` streams the raw handoff frame (O(memcpy), auto-falls back per
    replica when an upstream only speaks JSON); ``base64`` forces the legacy
    JSON envelope everywhere (the zero-copy gate's control arm)."""

    overload: Optional[OverloadConfig] = None
    """Serving-layer overload control (``serving/config.OverloadConfig``)
    applied to every fleet-built local replica when set; None = each
    replica keeps whatever its own ``ServingConfig.overload`` says."""

    autoscale: AutoscaleConfig = AutoscaleConfig()
    """Elastic scaling policy (``fleet/policy.py``)."""

    breaker: BreakerConfig = BreakerConfig()
    """Per-replica circuit breaker (``fleet/breaker.py``); every registered
    replica gets one, fed by probe failures and dispatch refusals."""

    supervisor: SupervisorConfig = SupervisorConfig()
    """Replica process supervision (``fleet/supervisor.py``)."""

    faults: FaultConfig = FaultConfig()
    """Deterministic fault injection (``fleet/faults.py``); disabled by
    default — the ``DSTPU_FAULTS`` env var (JSON ``FaultConfig`` body) can
    arm it without touching code."""
