"""Overload-control primitives for the serving layer.

Three small, engine-free pieces the scheduler composes (``serving/scheduler.py``)
— kept separate so the policy math is unit-testable without an engine:

- **priority classes**: every request carries one of :data:`PRIORITIES`
  (``interactive`` beats ``batch`` at every decision point: queue order,
  brownout clamping, stage-3 rejection, router hedging);
- :class:`RateEstimator` — an EWMA of the engine's *measured* token
  commit rate (prefill + decode lumped), the denominator for every
  queue-wait / deadline-feasibility estimate. Warmup-gated: admission
  control never rejects on a cold estimator;
- :class:`BrownoutController` — hysteresis-smoothed pressure (queue depth
  fraction vs KV occupancy, whichever is worse) mapped to staged
  degradation levels. Stages only move one way per update and re-arm below
  ``threshold - hysteresis``, so a noisy pressure signal cannot flap the
  fleet between degraded and normal service.

The stages (enforced by the scheduler, each counted and flagged in the
response ``degraded_mode`` — never silent):

- **0** normal service;
- **1** clamp ``max_new_tokens`` for batch-class requests;
- **2** additionally disable speculative extras (chunked ``decode_loop``
  dispatch falls back to one token per step);
- **3** additionally reject batch-class requests outright at submission
  (HTTP 429 + ``Retry-After``).
"""

import time
from typing import Optional, Sequence

PRIORITIES = ("interactive", "batch")
"""Priority classes, best first. ``interactive`` is the default: existing
clients that never heard of priorities keep first-class service."""

DEFAULT_PRIORITY = "interactive"


def priority_rank(priority: str) -> int:
    """Queue-ordering rank (lower schedules first)."""
    return PRIORITIES.index(priority)


def validate_priority(priority: Optional[str]) -> str:
    """Normalize/validate a wire-level priority field (None = default)."""
    if priority is None:
        return DEFAULT_PRIORITY
    if priority not in PRIORITIES:
        raise ValueError(f"unknown priority {priority!r} (know {PRIORITIES})")
    return priority


class RateEstimator:
    """EWMA of observed token throughput (tokens/s).

    ``observe(n)`` is called once per executed batch with the tokens it
    committed; the instantaneous rate is ``n / dt`` against the previous
    observation. ``rate`` is None until ``min_samples`` observations have
    landed — callers treat a cold estimator as "cannot prove anything"
    (admission control admits, shedding stands down).
    """

    def __init__(self, alpha: float = 0.25, min_samples: int = 4):
        self._alpha = alpha
        self._min_samples = min_samples
        self._ewma: Optional[float] = None
        self._samples = 0
        self._last_s: Optional[float] = None

    def observe(self, n_tokens: int, now: Optional[float] = None) -> None:
        if n_tokens <= 0:
            return
        now = time.monotonic() if now is None else now
        if self._last_s is None:
            self._last_s = now
            return  # first batch: no interval yet
        dt = now - self._last_s
        self._last_s = now
        if dt <= 0:
            return
        inst = n_tokens / dt
        self._ewma = (inst if self._ewma is None
                      else (1 - self._alpha) * self._ewma + self._alpha * inst)
        self._samples += 1

    @property
    def warm(self) -> bool:
        return self._ewma is not None and self._samples >= self._min_samples

    @property
    def rate(self) -> Optional[float]:
        """Tokens/s, or None while cold."""
        return self._ewma if self.warm else None

    def seconds_for(self, n_tokens: int) -> Optional[float]:
        """Estimated wall seconds to commit ``n_tokens``; None while cold."""
        rate = self.rate
        if rate is None or rate <= 0:
            return None
        return n_tokens / rate


class BrownoutController:
    """Staged degradation driven by a smoothed pressure signal.

    ``update(pressure)`` feeds one raw pressure sample in [0, 1] (the
    scheduler uses ``max(queue_fraction, kv_occupancy)``), smooths it with an
    EWMA, and maps it to a stage: the highest ``thresholds`` index the
    smoothed signal clears, +1. Hysteresis: a stage entered at ``t`` is only
    left when the signal falls below ``t - hysteresis``, so boundary noise
    cannot flap service modes.
    """

    def __init__(self, thresholds: Sequence[float] = (0.65, 0.85, 0.95),
                 hysteresis: float = 0.1, alpha: float = 0.3):
        if list(thresholds) != sorted(thresholds):
            raise ValueError(f"brownout thresholds must be ascending: {thresholds}")
        self._thresholds = tuple(thresholds)
        self._hysteresis = hysteresis
        self._alpha = alpha
        self._smoothed = 0.0
        self._stage = 0
        self.transitions = 0

    @property
    def stage(self) -> int:
        return self._stage

    @property
    def pressure(self) -> float:
        """The smoothed pressure signal (the stage driver)."""
        return self._smoothed

    @property
    def max_stage(self) -> int:
        return len(self._thresholds)

    def update(self, pressure: float) -> int:
        """Feed one raw pressure sample; returns the (possibly new) stage."""
        pressure = min(1.0, max(0.0, float(pressure)))
        self._smoothed = ((1 - self._alpha) * self._smoothed
                          + self._alpha * pressure)
        # escalate to the highest threshold cleared...
        stage = 0
        for i, t in enumerate(self._thresholds):
            if self._smoothed >= t:
                stage = i + 1
        # ...but de-escalate only past the hysteresis band of the CURRENT
        # stage's entry threshold (one band per stage: a signal hovering at a
        # boundary holds the stage instead of flapping)
        if stage < self._stage:
            hold = self._thresholds[self._stage - 1] - self._hysteresis
            if self._smoothed >= hold:
                stage = self._stage
        if stage != self._stage:
            self._stage = stage
            self.transitions += 1
        return self._stage
