"""Collective API tests (reference: tests/unit/comm/test_dist.py semantics, run on
the virtual 8-device mesh instead of a forked process pool)."""

import numpy as np
import pytest

import deepspeed_tpu.comm as dist
from deepspeed_tpu.comm import ReduceOp
from deepspeed_tpu.utils import groups


@pytest.fixture(autouse=True)
def mesh():
    groups.initialize_mesh(force=True)
    dist.init_distributed()
    yield


def test_all_reduce_sum():
    # shard i holds value i+1 → every shard becomes the sum 36
    x = np.arange(1.0, 9.0).reshape(8, 1).astype(np.float32)
    out = np.asarray(dist.all_reduce(x, op=ReduceOp.SUM))
    np.testing.assert_allclose(out, np.full((8, 1), 36.0))


def test_all_reduce_max():
    x = np.arange(8.0).reshape(8, 1).astype(np.float32)
    out = np.asarray(dist.all_reduce(x, op=ReduceOp.MAX))
    np.testing.assert_allclose(out, np.full((8, 1), 7.0))


def test_all_gather_into_tensor():
    x = np.arange(16.0).reshape(8, 2).astype(np.float32)  # each rank: [1,2]-slice
    out = np.asarray(dist.all_gather_into_tensor(x[:, None, :]))
    # torch semantics: concat of per-rank locals along dim0
    np.testing.assert_allclose(out.reshape(8, 2), x)


def test_reduce_scatter_tensor():
    # every rank holds the same [8*2] vector of ones → each rank's chunk = 8
    x = np.ones((8, 16), dtype=np.float32)
    out = np.asarray(dist.reduce_scatter_tensor(x))
    assert out.shape == (8, 2)
    np.testing.assert_allclose(out, np.full((8, 2), 8.0))


def test_all_to_all_single():
    # rank r sends chunk c to rank c; chunk value = 10*r + c
    x = np.zeros((8, 8), dtype=np.float32)
    for r in range(8):
        for c in range(8):
            x[r, c] = 10 * r + c
    out = np.asarray(dist.all_to_all_single(x))
    expect = x.T  # rank r ends with [10*c + r for c in range(8)]
    np.testing.assert_allclose(out, expect)


def test_broadcast():
    x = np.arange(8.0).reshape(8, 1).astype(np.float32)
    out = np.asarray(dist.broadcast(x, src=3))
    np.testing.assert_allclose(out, np.full((8, 1), 3.0))


def test_subgroup_all_reduce():
    groups.initialize_mesh(model_parallel_size=2, force=True)
    # group = 'model' axis (size 2): dim0 splits into 2 contiguous chunks, chunk g
    # being group-rank g's local tensor; result: each chunk = chunk sum.
    x = np.arange(8.0).reshape(8, 1).astype(np.float32)
    out = np.asarray(dist.all_reduce(x, group="model"))
    chunk_sum = x[:4] + x[4:]
    expect = np.concatenate([chunk_sum, chunk_sum])
    np.testing.assert_allclose(out, expect)


def test_comms_logger_records():
    dist.configure(enabled=True, verbose=False)
    x = np.ones((8, 4), dtype=np.float32)
    dist.all_reduce(x)
    summary = dist.comm.comms_logger.log_all(print_log=False)
    assert "all_reduce" in summary
    dist.configure(enabled=False)
