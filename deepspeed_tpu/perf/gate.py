"""The perf gate: flagship programs vs checked-in budgets, CPU-only.

``run_gate()`` builds each flagship program (perf/programs.py), extracts its
:class:`~deepspeed_tpu.perf.hlo_stats.HloStats`, checks them against the
checked-in budget file (perf/budgets/*.json) and returns a
:class:`GateReport`. The tier-1 pytest harness
(tests/unit/perf/test_gate.py, marker ``perfgate``) asserts the report is
clean; ``bin/dstpu_perfgate`` drives the same entry points interactively and
``rebaseline()`` rewrites the budget files on purpose.

When telemetry is active the gate also publishes ``perf_*`` gauges so a
long-lived process (CI sidecar, dev loop) can watch structural perf facts
drift over time, not just pass/fail.
"""

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from deepspeed_tpu.perf import budgets as budgets_mod
from deepspeed_tpu.perf.budgets import (Budget, Violation, budget_from_stats, check_stats,
                                        load_budget, write_budget)
from deepspeed_tpu.perf.chip_specs import DEFAULT_CHIP
from deepspeed_tpu.perf.hlo_stats import HloStats, stats_from_lowered
from deepspeed_tpu.perf.programs import FLAGSHIP_PROGRAMS, BuiltProgram, build_program
from deepspeed_tpu.perf.roofline import predict


@dataclass
class ProgramResult:
    name: str
    stats: HloStats
    roofline: dict
    violations: List[Violation] = field(default_factory=list)
    budget_created: str = ""
    budget_missing: bool = False
    meta: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations and not self.budget_missing


@dataclass
class GateReport:
    chip: str
    programs: Dict[str, ProgramResult] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.programs.values())

    @property
    def violations(self) -> List[Violation]:
        return [v for r in self.programs.values() for v in r.violations]

    def to_json(self) -> dict:
        return {
            "kind": "dstpu_perfgate_report",
            "chip": self.chip,
            "ok": self.ok,
            "programs": {
                name: {
                    "ok": r.ok,
                    "stats": r.stats.to_dict(),
                    "roofline": r.roofline,
                    "budget_created": r.budget_created,
                    "budget_missing": r.budget_missing,
                    "meta": r.meta,
                    "violations": [
                        {"metric": v.metric, "measured": v.measured,
                         "budget": v.budget, "limit": v.limit, "detail": v.detail}
                        for v in r.violations],
                } for name, r in self.programs.items()
            },
        }


def collect_stats(name: str, built: Optional[BuiltProgram] = None) -> ProgramResult:
    """Build one flagship program and extract stats + roofline (no budget
    check)."""
    built = built or build_program(name)
    stats = stats_from_lowered(built.lowered, name=built.name,
                               analytic_flops=built.analytic_flops)
    pred = predict(stats, DEFAULT_CHIP)
    return ProgramResult(name=built.name, stats=stats, roofline=pred.to_dict(),
                         meta=built.meta)


def run_gate(names: Optional[List[str]] = None, budgets_dir: Optional[str] = None,
             chip: str = DEFAULT_CHIP, publish: bool = True) -> GateReport:
    budgets_dir = budgets_dir or budgets_mod.default_budgets_dir()
    report = GateReport(chip=chip)
    for name in (names or list(FLAGSHIP_PROGRAMS)):
        result = collect_stats(name)
        try:
            budget = load_budget(budgets_dir, name)
        except FileNotFoundError:
            result.budget_missing = True
        else:
            result.budget_created = budget.created
            result.violations = check_stats(result.stats, budget)
        report.programs[name] = result
        if publish:
            _publish_telemetry(result, chip)
    return report


def check_program(name: str, stats: HloStats,
                  budgets_dir: Optional[str] = None) -> List[Violation]:
    """Check already-extracted stats against ``name``'s checked-in budget
    (the sensitivity tests feed deliberately-regressed stats through here)."""
    budget = load_budget(budgets_dir or budgets_mod.default_budgets_dir(), name)
    return check_stats(stats, budget)


def rebaseline(names: Optional[List[str]] = None, budgets_dir: Optional[str] = None,
               note: str = "") -> List[str]:
    """Rewrite budget files from current measurements. Deliberate by design:
    call it from ``bin/dstpu_perfgate rebaseline`` and review the diff."""
    budgets_dir = budgets_dir or budgets_mod.default_budgets_dir()
    paths = []
    for name in (names or list(FLAGSHIP_PROGRAMS)):
        result = collect_stats(name)
        budget = budget_from_stats(result.stats, program=name, note=note,
                                   roofline=result.roofline)
        paths.append(write_budget(budgets_dir, budget))
    return paths


def _publish_telemetry(result: ProgramResult, chip: str) -> None:
    """perf_* gauge families (cataloged in telemetry/catalog.py; no-op when
    telemetry is inactive)."""
    from deepspeed_tpu import telemetry
    if not telemetry.is_active():
        return
    reg = telemetry.get_registry()
    labels = {"program": result.name}
    reg.counter("perf_gate_runs_total", "Perf-gate program checks executed").inc()
    if result.violations:
        reg.counter("perf_gate_violations_total",
                    "Perf-gate budget violations detected").inc(len(result.violations))
    s = result.stats
    reg.gauge("perf_program_flops", "HLO cost-analysis FLOPs per program",
              labels=labels).set(s.flops)
    reg.gauge("perf_program_bytes_accessed", "HLO cost-analysis bytes moved per program",
              labels=labels).set(s.bytes_accessed)
    reg.gauge("perf_program_peak_bytes", "Live-buffer peak per program",
              labels=labels).set(s.peak_bytes)
    reg.gauge("perf_program_collective_bytes", "Collective payload bytes per program",
              labels=labels).set(s.collective_bytes_total)
    reg.gauge("perf_program_f32_dots", "f32-operand dots on the program's path",
              labels=labels).set(s.f32_dot_count)
    rl = result.roofline
    chip_labels = {"program": result.name, "chip": chip}
    reg.gauge("perf_predicted_step_seconds", "Roofline step-time lower bound",
              labels=chip_labels).set(rl["step_s"])
    reg.gauge("perf_predicted_mfu_bound", "Roofline MFU upper bound",
              labels=chip_labels).set(rl["mfu_bound"])


def write_report(report: GateReport, path: str) -> str:
    with open(path, "w") as f:
        json.dump(report.to_json(), f, indent=2, sort_keys=True)
        f.write("\n")
    return path
