"""Progressive Layer Drop (PLD).

Reference: ``deepspeed/runtime/progressive_layer_drop.py``
(ProgressiveLayerDrop:9 — θ(t) = (1-θ̄)·exp(-γ·t) + θ̄ updated each global
step) and the PLD paper's per-layer keep probability: layer i of L keeps with
``p_i = 1 - (i / L) · (1 - θ)`` so early layers are almost never dropped.

The engine instantiates this when ``progressive_layer_drop.enabled`` and
advances it at every gradient-accumulation boundary; models opt in with the
functional :func:`layer_drop` transform (a stochastic-depth residual skip,
traced — θ enters as a scalar array so no recompilation per step).
"""

import numpy as np


class ProgressiveLayerDrop:

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int):
        def _prob(x, g, t):
            return (1.0 - t) * np.exp(-g * x) + t

        self.current_theta = float(_prob(global_step, self.gamma, self.theta))


def keep_prob(layer_index: int, num_layers: int, theta):
    """Per-layer keep probability: 1 - (i/L)(1-θ)."""
    return 1.0 - (float(layer_index) / float(num_layers)) * (1.0 - theta)


def layer_drop(fn, x, rng, p_keep, *args, **kwargs):
    """Stochastic-depth residual skip (traced): with prob ``p_keep`` return
    ``fn(x, ...)``, else ``x``. At eval (rng=None) the block always runs —
    the reference's inference path likewise disables PLD."""
    import jax
    import jax.numpy as jnp

    if rng is None:
        return fn(x, *args, **kwargs)
    keep = jax.random.bernoulli(rng, jnp.asarray(p_keep, jnp.float32))
    return jax.lax.cond(keep, lambda t: fn(t, *args, **kwargs), lambda t: t, x)
