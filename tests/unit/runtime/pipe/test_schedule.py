"""Schedule instruction-stream tests (reference: tests/unit/runtime/pipe/
test_pipe_schedule.py)."""

import pytest

from deepspeed_tpu.runtime.pipe import schedule as S


def _flat(sched):
    return [cmd for step in sched for cmd in step]


def test_inference_schedule_counts():
    sched = S.InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
    cmds = _flat(sched)
    fwd = [c for c in cmds if isinstance(c, S.ForwardPass)]
    assert len(fwd) == 4
    sends = [c for c in cmds if isinstance(c, S.SendActivation)]
    assert len(sends) == 4  # stage 0 sends every microbatch


def test_train_schedule_each_mb_fwd_and_bwd_once():
    for stages in (2, 4):
        for stage_id in range(stages):
            sched = S.TrainSchedule(micro_batches=8, stages=stages, stage_id=stage_id)
            cmds = _flat(sched)
            fwd = [c.buffer_id for c in cmds if isinstance(c, S.ForwardPass)]
            bwd = [c.buffer_id for c in cmds if isinstance(c, S.BackwardPass)]
            assert len(fwd) == 8, f"stage {stage_id}/{stages}"
            assert len(bwd) == 8
            # single optimizer step at the very end
            steps = [c for c in cmds if isinstance(c, S.OptimizerStep)]
            assert len(steps) == 1
            assert isinstance(cmds[-1], S.OptimizerStep)


def test_train_schedule_fwd_before_bwd():
    sched = S.TrainSchedule(micro_batches=4, stages=2, stage_id=1)
    seen_fwd = set()
    for step in sched:
        for cmd in step:
            if isinstance(cmd, S.ForwardPass):
                seen_fwd.add(cmd.buffer_id)
            if isinstance(cmd, S.BackwardPass):
                assert cmd.buffer_id in seen_fwd  # backward only after its forward


def test_train_schedule_1f1b_inflight_bound():
    """In-flight microbatches never exceed the remaining pipeline depth."""
    stages, mb = 4, 16
    for stage_id in range(stages):
        sched = S.TrainSchedule(micro_batches=mb, stages=stages, stage_id=stage_id)
        inflight = 0
        peak = 0
        for step in sched:
            for cmd in step:
                if isinstance(cmd, S.ForwardPass):
                    inflight += 1
                if isinstance(cmd, S.BackwardPass):
                    inflight -= 1
                peak = max(peak, inflight)
        assert peak <= stages - stage_id + 1


def test_num_pipe_buffers():
    sched = S.TrainSchedule(micro_batches=8, stages=4, stage_id=0)
    assert sched.num_pipe_buffers() == 4
    sched = S.TrainSchedule(micro_batches=8, stages=4, stage_id=3)
    assert sched.num_pipe_buffers() == 2


def test_instruction_repr_and_eq():
    a = S.ForwardPass(buffer_id=1)
    b = S.ForwardPass(buffer_id=1)
    c = S.ForwardPass(buffer_id=2)
    assert a == b and a != c
    assert "ForwardPass" in repr(a)


@pytest.mark.parametrize("stages,mb", [(2, 4), (4, 4), (4, 8), (8, 3)])
def test_train_schedule_cross_stage_pairing(stages, mb):
    """Every send at tick t pairs with the neighbor stage's recv at the SAME t
    for the SAME microbatch — required by a step-synchronized executor.

    Buffer ids are stage-local (num_pipe_buffers differs per stage), so pairing
    is checked on microbatch ids recovered from the work_at tick equation:
    SendActivation at tick t carries the sender's forward work of tick t-1;
    the receiver's RecvActivation at tick t targets its own current forward mb.
    SendGrad symmetrically carries the backward work of tick t-1.
    """
    scheds = [S.TrainSchedule(micro_batches=mb, stages=stages, stage_id=s)
              for s in range(stages)]
    streams = [list(s) for s in scheds]
    n_ticks = len(streams[0])
    assert all(len(st) == n_ticks for st in streams)

    for t in range(n_ticks):
        for s in range(stages):
            for cmd in streams[s][t]:
                if isinstance(cmd, S.SendActivation):
                    _, sent_mb = scheds[s].work_at(t - 1)
                    recvs = [c for c in streams[s + 1][t] if isinstance(c, S.RecvActivation)]
                    assert len(recvs) == 1, f"tick {t}: stage {s} SendActivation unpaired"
                    _, recv_mb = scheds[s + 1].work_at(t)
                    assert recv_mb == sent_mb, f"tick {t}: act mb {sent_mb} vs {recv_mb}"
                if isinstance(cmd, S.SendGrad):
                    _, sent_mb = scheds[s].work_at(t - 1)
                    recvs = [c for c in streams[s - 1][t] if isinstance(c, S.RecvGrad)]
                    assert len(recvs) == 1, f"tick {t}: stage {s} SendGrad unpaired"
                    _, recv_mb = scheds[s - 1].work_at(t)
                    assert recv_mb == sent_mb, f"tick {t}: grad mb {sent_mb} vs {recv_mb}"
    # conversely: every recv is fed by exactly one send at the same tick
    for s in range(stages):
        for t in range(n_ticks):
            for cmd in streams[s][t]:
                if isinstance(cmd, S.RecvActivation):
                    assert sum(isinstance(c, S.SendActivation) for c in streams[s - 1][t]) == 1
                if isinstance(cmd, S.RecvGrad):
                    assert sum(isinstance(c, S.SendGrad) for c in streams[s + 1][t]) == 1
    # and globally: each of the mb microbatches crosses each boundary exactly once
    for s in range(1, stages):
        n_recv = sum(isinstance(c, S.RecvActivation) for st in streams[s] for c in st)
        assert n_recv == mb


@pytest.mark.parametrize("stages,mb", [(2, 4), (4, 6)])
def test_train_schedule_work_equation(stages, mb):
    """The closed-form work_at equation: forwards arrive in order, one tick
    later per stage; backwards climb one tick per stage."""
    for s in range(stages):
        sched = S.TrainSchedule(micro_batches=mb, stages=stages, stage_id=s)
        fwd_ticks = {}
        bwd_ticks = {}
        for t in range(2 * (mb + stages - 1)):
            d, m = sched.work_at(t)
            if 0 <= m < mb:
                (fwd_ticks if d == S.FORWARD else bwd_ticks)[m] = t
        assert fwd_ticks[0] == s
        assert all(fwd_ticks[m + 1] - fwd_ticks[m] == 2 for m in range(mb - 1))
        assert bwd_ticks[0] == 2 * stages - s - 1


@pytest.mark.parametrize("stages,mb", [(2, 4), (4, 3)])
def test_inference_schedule_cross_stage_pairing(stages, mb):
    """Same same-tick send/recv invariant as TrainSchedule, forward-only."""
    streams = [list(S.InferenceSchedule(micro_batches=mb, stages=stages, stage_id=s))
               for s in range(stages)]
    n_ticks = len(streams[0])
    for t in range(n_ticks):
        for s in range(stages):
            n_send = sum(isinstance(c, S.SendActivation) for c in streams[s][t])
            if s + 1 < stages:
                n_recv = sum(isinstance(c, S.RecvActivation) for c in streams[s + 1][t])
                assert n_send == n_recv, f"tick {t}, boundary {s}->{s+1}"
    for s in range(1, stages):
        assert sum(isinstance(c, S.RecvActivation) for st in streams[s] for c in st) == mb
        assert sum(isinstance(c, S.SendActivation) for st in streams[s - 1] for c in st) == mb
