"""Per-layer execution tracer (fork addition).

Reference: ``deepspeed/inference/v2/tracer.py`` (Tracer:37, BatchTraceSummary:26,
``record(name)`` context manager used inside model forwards; CUDA-event timing).

TPU translation: there are no CUDA events, and a fused jitted forward has no
internal host-visible boundaries. When tracing is enabled the model runs in
*segmented* mode — embed / per-layer attn / ffn / moe phases execute as separate
device computations with ``block_until_ready`` barriers, and ``record`` takes
wall-clock timestamps around each. Tracing therefore perturbs performance (the
reference's CUDA events cost less but also perturb); it reports true per-phase
device times in microseconds, matching the reference's summary schema.

Memory: traces accumulate in a bounded ring buffer (``max_batches``, default
1024) so long-running serving cannot leak; consumers should prefer
``drain_summaries()`` which frees what it returns. When a
``span_recorder`` is attached (engine-owned telemetry session) — or, without
one, while a globally-configured session is active — every recorded phase
also emits a Chrome-trace span under the ``inference`` category.
"""

import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, List

from deepspeed_tpu.telemetry import get_span_recorder as _tel_get_spans
from deepspeed_tpu.telemetry import now_us

RECORD_NAMES = ["attn", "ffn", "moe_a2a_1", "moe_a2a_2", "moe_ffn", "moe_a2a_3"]

DEFAULT_MAX_TRACE_BATCHES = 1024


@dataclass
class BatchTraceHolder:
    batch_id: int
    num_layers: int
    is_empty_run: bool
    seen_tokens: Any = field(default_factory=list)
    in_flight_tokens: Any = field(default_factory=list)
    traces: Any = field(default_factory=list)  # (name, elapsed_us)
    uids: Any = field(default_factory=list)  # constituent request/sequence uids
    uids_view: Any = None  # one frozen copy shared by all this batch's spans


@dataclass
class BatchTraceSummary:
    batch_id: int
    is_empty_run: bool
    num_layers: int
    seen_tokens: List[int]
    in_flight_tokens: List[int]
    record_names: List[str]
    record_exec_times: Any  # [num_layers][len(record_names)] in us
    embed: int
    unembed: int
    uids: List[int] = field(default_factory=list)


class Tracer:

    def __init__(self, max_batches: int = DEFAULT_MAX_TRACE_BATCHES, span_recorder=None):
        self._batch_counter = 0
        # ring buffer: long-running serving must not grow host memory per
        # batch; past capacity the oldest unconsumed trace is dropped
        self._batch_traces = deque(maxlen=max(1, max_batches))
        self._cur: BatchTraceHolder = None
        self.span_recorder = span_recorder

    @property
    def pending_batches(self) -> int:
        return len(self._batch_traces)

    def init_batch(self, is_empty_run: bool, num_layers: int) -> None:
        self._cur = BatchTraceHolder(self._batch_counter, num_layers, is_empty_run)
        self._batch_counter += 1
        self._batch_traces.append(self._cur)

    def add_sequence(self, seq_desc) -> None:
        self._cur.seen_tokens.append(seq_desc.seen_tokens)
        self._cur.in_flight_tokens.append(seq_desc.in_flight_tokens)
        # descriptors are duck-typed here; -1 marks one with no engine uid
        self._cur.uids.append(int(getattr(seq_desc, "tracking_id", -1)))

    def add_trace(self, name: str, elapsed_us: int, ts_us: int = None) -> None:
        if self._cur is None:
            return
        self._cur.traces.append((name, elapsed_us))
        # the recorder bound at construction (engine-owned session) — or a
        # globally-configured session's, resolved per record like engine_v2's
        # span fallback, so the process-wide-configure pattern gets per-layer
        # phases too; disabled telemetry pays one global read
        spans = self.span_recorder if self.span_recorder is not None else _tel_get_spans()
        if spans is not None:
            # uids join each per-layer phase against the serving request
            # traces composed into this ragged batch; snapshot ONCE on the
            # first phase (sequences are all inserted before the forward
            # runs) — num_layers * len(RECORD_NAMES) spans share the copy
            uids = self._cur.uids_view
            if uids is None:
                uids = self._cur.uids_view = [int(u) for u in self._cur.uids]
            spans.record(name, cat="inference", ts_us=ts_us,
                         dur_us=elapsed_us,
                         args={"batch_id": self._cur.batch_id,
                               "uids": uids})

    def _summarize(self, bt: BatchTraceHolder) -> BatchTraceSummary:
        traces = list(bt.traces)
        embed = unembed = 0
        if not bt.is_empty_run and traces:
            if traces and traces[0][0] == "embed":
                embed = traces.pop(0)[1]
            if traces and traces[-1][0] == "unembed":
                unembed = traces.pop()[1]
        name_idx = {n: i for i, n in enumerate(RECORD_NAMES)}
        per_layer = max(1, len(traces) // max(1, bt.num_layers))
        exec_times = []
        for li in range(bt.num_layers):
            row = [0] * len(RECORD_NAMES)
            for name, us in traces[li * per_layer:(li + 1) * per_layer]:
                if name in name_idx:
                    row[name_idx[name]] = us
            exec_times.append(row)
        return BatchTraceSummary(batch_id=bt.batch_id,
                                 is_empty_run=bt.is_empty_run,
                                 num_layers=bt.num_layers,
                                 seen_tokens=bt.seen_tokens,
                                 in_flight_tokens=bt.in_flight_tokens,
                                 record_names=RECORD_NAMES,
                                 record_exec_times=exec_times,
                                 embed=embed,
                                 unembed=unembed,
                                 uids=list(bt.uids))

    def batch_summaries(self):
        """Summaries of everything still buffered (non-destructive)."""
        for bt in self._batch_traces:
            yield self._summarize(bt)

    def drain_summaries(self) -> List[BatchTraceSummary]:
        """Summarize AND free the consumed traces — the long-running-serving
        consumption API (a periodic drain keeps the ring from ever dropping)."""
        out = []
        while self._batch_traces:
            bt = self._batch_traces.popleft()
            out.append(self._summarize(bt))
            if bt is self._cur:
                self._cur = None
        return out


_TRACER = None


def set_tracer(tracer) -> None:
    global _TRACER
    _TRACER = tracer


def get_tracer():
    return _TRACER


@contextmanager
def record(name: str):
    """Time a phase (no-op when tracing is disabled). The body must end with a
    device sync (block_until_ready) for the number to mean device time."""
    tracer = get_tracer()
    if tracer is None:
        yield
        return
    t0 = time.perf_counter()
    ts0 = now_us()
    try:
        yield
    finally:
        tracer.add_trace(name, int((time.perf_counter() - t0) * 1e6), ts_us=ts0)
