"""Router global queue: priority/deadline-ordered queued dispatch with
replica-pull semantics (ROADMAP 3c, first half).

Queued work lives HERE, at the router, instead of in per-replica submission
queues: a request is only handed to a replica when that replica has a free
dispatch slot, so the router — which sees the whole fleet — decides *which*
queued request runs next (interactive before batch, earliest deadline first)
instead of whichever replica queue it happened to be pushed into. That
ordering is the substrate every overload behavior builds on: deadline expiry
while queued is detected centrally (429 + ``Retry-After``, before any
replica work), and a burst parks at the router rather than fanning out into
N replica queues that each drain blindly.

No background thread: grants happen inline — ``acquire`` tries to place the
entry immediately, and every ``release`` (a leg finished, freeing its slot)
pumps the queue on the releasing thread. The tier-1 formulation: fully
event-driven, nothing to wake up, deterministic under test.

Capacity model: each replica may hold at most ``max_inflight`` concurrently
granted legs (continuous batching makes a replica happily run several; the
cap keeps one replica from absorbing a burst the rest of the fleet could
share). Candidate health/breaker filtering stays the router's job — every
entry carries a ``pool_fn`` re-evaluated at each pump, so replicas joining,
leaving, or tripping breakers are seen immediately.

``inject_phantoms`` is the chaos harness's ``overload_burst`` hook: phantom
entries occupy queue capacity for a bounded hold, are never granted, and
expire loudly through the same accounting as real entries.
"""

import itertools
import threading
import time
from typing import Callable, List, Optional, Sequence

from deepspeed_tpu.serving.overload import priority_rank

_ENTRY_SEQ = itertools.count()


class GlobalQueueFull(RuntimeError):
    """The router global queue is at capacity; ``retry_after_s`` is the
    grant-rate-derived backoff (HTTP 429)."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class QueueWaitExpired(RuntimeError):
    """The entry's deadline (or the acquire timeout) passed while it waited
    for a replica — router-level shedding, before any replica work."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class _Entry:
    __slots__ = ("seq", "priority", "deadline", "enq_s", "pool_fn",
                 "session_key", "event", "replica", "phantom", "hint")

    def __init__(self, pool_fn, priority: str, deadline: Optional[float],
                 session_key: Optional[str], phantom: bool = False,
                 hint=None):
        self.seq = next(_ENTRY_SEQ)
        self.priority = priority
        self.deadline = deadline          # absolute monotonic, None = none
        self.enq_s = time.monotonic()
        self.pool_fn = pool_fn
        self.session_key = session_key
        self.event = threading.Event()
        self.replica = None               # set under the queue lock at grant
        self.phantom = phantom
        self.hint = hint                  # opaque placement hint for pick

    @property
    def order_key(self):
        return (priority_rank(self.priority),
                self.deadline if self.deadline is not None else float("inf"),
                self.seq)


class GlobalQueue:
    """Priority/deadline-ordered dispatch queue with per-replica slot caps.

    ``pick`` is the router's replica-selection policy (affinity /
    least-loaded / slow-demotion) applied to the free-slot candidates of the
    entry being granted.
    """

    def __init__(self, max_inflight: int, capacity: int, pick: Callable,
                 retry_after_floor_s: float = 0.5,
                 retry_after_cap_s: float = 30.0,
                 metrics=None):
        self._max_inflight = max_inflight
        self._capacity = capacity
        self._pick = pick
        self._retry_floor = retry_after_floor_s
        self._retry_cap = retry_after_cap_s
        self._metrics = metrics
        self._lock = threading.Lock()
        # poll-path pumps are pure backstops (grants are event-driven on
        # release): at most one waiter runs one at a time, the rest skip —
        # otherwise N waiters x M entries re-evaluate every pool each tick
        self._pump_gate = threading.Lock()
        self._entries: List[_Entry] = []
        self._slots = {}                  # replica id -> granted legs
        self._grants = 0
        self._expired = 0
        self._admission_sheds = 0
        self._phantoms_injected = 0
        # EWMA of the inter-grant interval: the queue's drain clock, the
        # Retry-After denominator (None until the second grant)
        self._last_grant_s: Optional[float] = None
        self._grant_interval_ewma: Optional[float] = None

    # ----------------------------------------------------------------- stats --
    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def slots_in_use(self, replica_id: str) -> int:
        with self._lock:
            return self._slots.get(replica_id, 0)

    def retry_after_s(self) -> float:
        """Backoff estimate from the measured grant rate: depth × the EWMA
        inter-grant interval, bounded. No grants yet = the floor scaled by
        depth (some signal beats none)."""
        with self._lock:
            depth = len(self._entries)
            interval = self._grant_interval_ewma
        est = (depth * interval if interval is not None
               else self._retry_floor * (1 + depth))
        return min(self._retry_cap, max(self._retry_floor, est))

    def describe(self) -> dict:
        with self._lock:
            return {"depth": len(self._entries),
                    "slots": {k: v for k, v in sorted(self._slots.items()) if v},
                    "grants": self._grants,
                    "expired": self._expired,
                    "admission_sheds": self._admission_sheds,
                    "phantoms_injected": self._phantoms_injected,
                    "retry_after_s": None if self._grant_interval_ewma is None
                    else round(len(self._entries) * self._grant_interval_ewma, 3)}

    # --------------------------------------------------------------- acquire --
    def acquire(self, pool_fn: Callable[[], Sequence], *,
                priority: str = "interactive",
                deadline_s: Optional[float] = None,
                session_key: Optional[str] = None,
                timeout_s: float = 30.0,
                hint=None):
        """Wait for a replica with a free slot (priority/deadline order);
        returns the granted replica, whose slot the caller MUST release via
        :meth:`release` when the leg finishes. ``deadline_s`` is the
        remaining client deadline: expiring while queued raises
        :class:`QueueWaitExpired` (router-level shedding, nothing dispatched).
        ``hint`` is an opaque placement hint forwarded to ``pick`` at grant
        time (cache-aware routing threads the request's prefix chain here).
        """
        now = time.monotonic()
        entry = _Entry(pool_fn, priority,
                       now + deadline_s if deadline_s is not None else None,
                       session_key, hint=hint)
        with self._lock:
            # admission estimate: with a warm grant clock, an entry whose
            # expected grant wait (depth x the EWMA inter-grant interval)
            # already exceeds its deadline is shed HERE — a rejection at
            # enqueue costs nothing, an expiry at the deadline costs a
            # parked slot in every client's latency budget
            if (deadline_s is not None and self._grant_interval_ewma is not None
                    and len(self._entries) * self._grant_interval_ewma
                    > deadline_s):
                est = len(self._entries) * self._grant_interval_ewma
                depth = len(self._entries)
                # an admission shed IS an expiry (counted in both the
                # fleet_global_queue_expired metric and describe()["expired"]
                # — the two surfaces must agree); admission_sheds is the
                # subset that never waited
                self._admission_sheds += 1
                self._expired += 1
            else:
                est = None
                full = len(self._entries) >= self._capacity
                if not full:
                    self._entries.append(entry)
        if est is not None:
            self._note_expired()
            raise QueueWaitExpired(
                f"queue admission: estimated grant wait {est:.2f}s exceeds "
                f"the {deadline_s:.2f}s deadline at depth {depth}",
                retry_after_s=self.retry_after_s())
        if full:
            # retry_after_s() takes the (non-reentrant) lock: raise outside it
            raise GlobalQueueFull(
                f"router global queue at capacity ({self._capacity})",
                retry_after_s=self.retry_after_s())
        if self._metrics:
            self._metrics.global_queue_depth.set(self.depth)
        self._pump()
        wait_deadline = now + (min(timeout_s, deadline_s)
                               if deadline_s is not None else timeout_s)
        while not entry.event.wait(timeout=min(0.25, max(0.0, wait_deadline
                                                         - time.monotonic()) or 0.001)):
            self._maybe_pump()  # replicas may have become healthy without
            # a release; one concurrent backstop pump is plenty
            if time.monotonic() >= wait_deadline:
                with self._lock:
                    # entry.replica is assigned under the lock at grant,
                    # strictly before event.set() runs outside it — checking
                    # the event here would miss a just-granted entry, raise
                    # on the remove, and leak the granted slot forever
                    if entry.replica is not None:
                        break  # granted in the race window: keep the slot
                    self._entries.remove(entry)
                    self._expired += 1
                self._note_expired()
                raise QueueWaitExpired(
                    f"queue wait exceeded "
                    f"{'deadline' if entry.deadline is not None else 'timeout'} "
                    f"after {time.monotonic() - entry.enq_s:.2f}s at depth "
                    f"{self.depth}", retry_after_s=self.retry_after_s())
        wait = time.monotonic() - entry.enq_s
        if self._metrics:
            self._metrics.global_queue_wait.observe(wait)
            self._metrics.global_queue_depth.set(self.depth)
        return entry.replica

    def release(self, replica_id: str) -> None:
        """A granted leg finished (or failed to dispatch): free its slot and
        pump — the freed capacity goes to the best queued entry NOW, on this
        thread (pull dispatch)."""
        with self._lock:
            n = self._slots.get(replica_id, 0)
            if n <= 1:
                self._slots.pop(replica_id, None)
            else:
                self._slots[replica_id] = n - 1
        self._pump()

    # ------------------------------------------------------------------ pump --
    def _maybe_pump(self) -> None:
        """Run a pump only if no other thread is mid-pump (the poll-path
        backstop); release() keeps calling :meth:`_pump` directly — freed
        capacity must be granted NOW, not next tick."""
        if self._pump_gate.acquire(blocking=False):
            try:
                self._pump()
            finally:
                self._pump_gate.release()

    def _pump(self) -> None:
        """Grant every placeable entry, best first. Candidates are computed
        OUTSIDE the lock (``pool_fn`` may probe replicas over sockets); the
        grant itself re-validates under the lock."""
        now = time.monotonic()
        granted_this_pass = 0
        with self._lock:
            snapshot = sorted(self._entries, key=lambda e: e.order_key)
        for entry in snapshot:
            if entry.phantom:
                if entry.deadline is not None and now >= entry.deadline:
                    with self._lock:
                        if entry in self._entries:
                            self._entries.remove(entry)
                            self._expired += 1
                    self._note_expired()
                continue  # phantoms are never granted
            try:
                pool = list(entry.pool_fn())
            except Exception:  # pragma: no cover - a dying pool_fn must not
                continue       # wedge the pump for the other entries
            with self._lock:
                if entry not in self._entries:
                    continue  # granted/expired by a racing pump
                candidates = [r for r in pool
                              if self._slots.get(r.id, 0) < self._max_inflight]
                if not candidates:
                    continue
                # pick sees the full pool and the entry's deadline too: a
                # None verdict means "rather wait" (e.g. every free slot is
                # on a demotion-grade slow replica and the entry carries a
                # deadline a doomed grant would burn)
                if entry.hint is not None:
                    # only pass the kwarg when a hint exists: custom pick
                    # callables predating cache-aware routing keep working
                    replica = self._pick(candidates, entry.session_key,
                                         pool=pool, deadline=entry.deadline,
                                         hint=entry.hint)
                else:
                    replica = self._pick(candidates, entry.session_key,
                                         pool=pool, deadline=entry.deadline)
                if replica is None:
                    continue
                self._slots[replica.id] = self._slots.get(replica.id, 0) + 1
                self._entries.remove(entry)
                entry.replica = replica
                self._grants += 1
                granted_this_pass += 1
            entry.event.set()
            if self._metrics:
                self._metrics.global_queue_grants.inc()
        if granted_this_pass:
            # ONE amortized EWMA update per pass — (elapsed since the last
            # grant activity) / (grants this pass). Per-grant updates would
            # feed k near-zero intervals for a k-grant pass, shrinking the
            # EWMA by 0.7^k and collapsing the Retry-After / admission
            # estimate exactly when the queue is bursty.
            end_s = time.monotonic()
            with self._lock:
                if self._last_grant_s is not None:
                    interval = max(0.0, end_s - self._last_grant_s) \
                        / granted_this_pass
                    self._grant_interval_ewma = (
                        interval if self._grant_interval_ewma is None
                        else 0.7 * self._grant_interval_ewma + 0.3 * interval)
                self._last_grant_s = end_s

    def _note_expired(self) -> None:
        if self._metrics:
            self._metrics.global_queue_expired.inc()
            self._metrics.global_queue_depth.set(self.depth)

    # -------------------------------------------------------------- phantoms --
    def inject_phantoms(self, n: int, hold_s: float) -> int:
        """The ``overload_burst`` chaos hook: ``n`` phantom batch-priority
        entries that occupy queue capacity for ``hold_s`` then expire (never
        granted). Returns how many fit under the capacity cap."""
        injected = 0
        deadline = time.monotonic() + hold_s
        with self._lock:
            for _ in range(n):
                if len(self._entries) >= self._capacity:
                    break
                entry = _Entry(None, "batch", None, None, phantom=True)
                entry.deadline = deadline
                self._entries.append(entry)
                injected += 1
            self._phantoms_injected += injected
        if self._metrics:
            self._metrics.global_queue_depth.set(self.depth)
        return injected
