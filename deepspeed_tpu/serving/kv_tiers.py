"""Serving-side controller for the tiered KV memory ladder.

The mechanism lives in ``inference/v2/ragged/tiering.py`` (the host→disk
store under ``BlockedKVCache``); this module is the *policy* layer the
serving scheduler drives:

- at scheduler construction, retrofit the engine's tiered store with the
  operator's budget/spill config (the engine is built before the serving
  config arrives — ``TieredKVStore.configure`` exists for exactly this);
- under KV pressure, demote in preference order: prefix-trie nodes
  device→host first (idle cached state, promotes back on the next hit),
  then already-offloaded sessions host→disk (coldest first) — freeing
  capacity WITHOUT discarding anything, which is what lets brownout demote
  before it sheds;
- keep the ``serving_kv_tier_*`` gauges current and assemble the per-tier
  stats block ``/v1/stats`` publishes (what ``dstpu_report --kv`` renders).
"""

from typing import Optional

from deepspeed_tpu.serving.config import KVTierConfig


class KVTierController:
    """Policy driver over one engine's :class:`TieredKVStore`.

    All demotion entry points run on the scheduler (engine-owning) thread —
    the same thread that owns every other trie/allocator touch. The stats
    snapshot is safe from any thread (the store locks internally; counters
    are scalar reads).
    """

    def __init__(self, engine, config: KVTierConfig, metrics=None):
        self._engine = engine
        self._config = config
        self._metrics = metrics
        kv = engine._state_manager.kv_cache
        kv.configure_tiering(spill_dir=config.spill_dir,
                             host_bytes=config.host_bytes)
        self._kv = kv
        self.demotions = 0        # device blocks demoted by pressure policy
        self.promotions_seen = 0  # trie promotions observed at last gauge tick

    @property
    def demote_batch(self) -> int:
        return self._config.demote_batch

    # ------------------------------------------------------------- demotion --
    def demote_for_pressure(self, prefix_cache, active_requests) -> int:
        """One pressure-relief pass: demote up to ``demote_batch`` device
        blocks' worth of idle cached state down the ladder. Trie nodes go
        first (device→host — each frees one device block immediately); any
        remaining budget pushes the coldest host-resident *offloaded*
        sessions to disk (freeing host budget so future demotions have
        somewhere to land). Returns the number of demotions performed —
        the brownout controller skips shedding on any tick where this is
        non-zero."""
        budget = self._config.demote_batch
        demoted = 0
        if prefix_cache is not None:
            demoted += prefix_cache.demote(budget)
        if demoted < budget:
            demoted += self._demote_offloaded(active_requests,
                                              budget - demoted)
        if demoted:
            self.demotions += demoted
            if self._metrics:
                self._metrics.kv_tier_demotions.inc(demoted)
        return demoted

    def _demote_offloaded(self, active_requests, budget: int) -> int:
        """Push the coldest host-tier offloaded sessions toward disk."""
        sm = self._engine._state_manager
        candidates = [r for r in active_requests
                      if r.uid is not None and sm.is_offloaded(r.uid)
                      and sm.sequence_tier(r.uid) == "host"]
        candidates.sort(key=lambda r: r._last_touch_s)
        demoted = 0
        for req in candidates[:budget]:
            if sm.demote_sequence(req.uid):
                demoted += 1
                if self._metrics:
                    self._metrics.kv_tier_disk_demotions.inc()
        return demoted

    # ---------------------------------------------------------------- stats --
    def update_gauges(self, prefix_cache=None) -> None:
        if not self._metrics:
            return
        s = self._kv.tier_stats()
        self._metrics.kv_tier_device_blocks.set(
            self._kv.num_blocks - self._kv.free_blocks)
        self._metrics.kv_tier_host_blocks.set(s["host_blocks"])
        self._metrics.kv_tier_disk_blocks.set(s["disk_blocks"])
        if prefix_cache is not None:
            promotions = prefix_cache.tier_promotions
            if promotions > self.promotions_seen:
                self._metrics.kv_tier_promotions.inc(
                    promotions - self.promotions_seen)
                self.promotions_seen = promotions

    def stats(self, prefix_cache=None) -> dict:
        """The ``/v1/stats`` tier block: store occupancy per tier plus the
        policy-level counters (``dstpu_report --kv`` renders this)."""
        doc = dict(self._kv.tier_stats())
        doc["enabled"] = True
        doc["device_blocks_used"] = self._kv.num_blocks - self._kv.free_blocks
        doc["device_blocks_total"] = self._kv.num_blocks
        doc["pressure_demotions"] = self.demotions
        if prefix_cache is not None:
            doc["trie_offloaded_nodes"] = prefix_cache.offloaded_nodes
            doc["trie_demotions"] = prefix_cache.tier_demotions
            doc["trie_promotions"] = prefix_cache.tier_promotions
        return doc


def maybe_create(engine, config: KVTierConfig,
                 metrics=None) -> Optional[KVTierController]:
    """None when tiering is disabled — the scheduler's hot paths stay one
    ``is None`` check, mirroring the ``ServingMetrics.maybe_create`` idiom."""
    if not config.enabled:
        return None
    return KVTierController(engine, config, metrics=metrics)
