"""Inference v1 engine tests (reference: tests/unit/inference/test_inference.py —
here exercised with a flax module instead of HF torch models)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.utils import groups


@pytest.fixture(autouse=True)
def mesh():
    groups.initialize_mesh(force=True)
    yield


def _tiny_mlp():
    import flax.linen as nn
    import jax

    class MLP(nn.Module):

        @nn.compact
        def __call__(self, x):
            x = nn.Dense(32)(x)
            x = nn.gelu(x)
            return nn.Dense(8)(x)

    model = MLP()
    x = np.ones((2, 8), np.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    return model, params, x


def test_init_inference_forward():
    model, params, x = _tiny_mlp()
    engine = deepspeed_tpu.init_inference({"module": model, "params": params}, dtype="float32")
    out = engine(x)
    assert out.shape == (2, 8)
    # matches the raw module
    import jax
    ref = model.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_init_inference_bf16_cast():
    model, params, x = _tiny_mlp()
    engine = deepspeed_tpu.init_inference({"module": model, "params": params}, dtype="bfloat16")
    import jax.numpy as jnp
    leaf = next(iter(engine.params["Dense_0"].values()))
    assert leaf.dtype == jnp.bfloat16
    out = engine(x)
    assert out.shape == (2, 8)


def test_generate_without_module_support_raises():
    model, params, x = _tiny_mlp()
    engine = deepspeed_tpu.init_inference({"module": model, "params": params})
    with pytest.raises(NotImplementedError):
        engine.generate(x)
