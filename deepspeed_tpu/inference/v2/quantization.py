"""ZeRO-Inference-style weight quantization for the ragged engine.

Reference: the ZeRO-Inference release (reference README.md:17 — "20x faster
inference" via weight quantization + KV-cache offload) and
``deepspeed/inference/quantization`` (per-channel symmetric int8/int4 of the
matmul weights, dequantized on use; int4 kernels in
``csrc/quantization/quantize_intX.cu``).

TPU formulation: quantized leaves are stored int8 — or int4, packed 8
nibbles to an int32 word along the contraction axis (int32-backed because
Mosaic/XLA-TPU handle sub-byte minor-dim reshapes poorly) — in HBM with
per-output-channel fp scales; ``dequantize_tree`` runs *inside* the jitted
forward, so XLA fuses the unpack/convert/scale into each weight's consumer —
weights stream from HBM at 1 (or 0.5) byte/element (the decode-path win;
matmuls stay MXU bf16). Pytree-native: a quantized leaf becomes a
``{QKEY|Q4KEY, SKEY, DKEY}`` dict subtree, invisible to checkpointing and
sharding machinery.
"""

from typing import Any

import numpy as np

QKEY = "__wq_int8__"
Q4KEY = "__wq_int4x8__"  # [..., K//8, N] int32, 8 consecutive-K nibbles/word
SKEY = "__wq_scale__"
DKEY = "__wq_dtype__"


def _quantize_leaf(w):
    import jax.numpy as jnp
    # per-output-channel symmetric int8: reduce the contraction axis (-2),
    # keep leading (expert/stack) dims
    scale = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    # dtype marker as a 0-d array so the subtree stays a pure array pytree
    return {QKEY: q, SKEY: scale, DKEY: jnp.zeros((), w.dtype)}


def _quantize_leaf_int4(w):
    """Per-output-channel symmetric int4 ([-7, 7]); 8 consecutive contraction-
    axis nibbles packed into one int32 word (0.5 bytes/element at rest)."""
    import jax.numpy as jnp
    scale = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 7.0
    scale = jnp.maximum(scale, 1e-12).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -7, 7).astype(jnp.int32)
    K, N = w.shape[-2], w.shape[-1]
    q = q.reshape(*w.shape[:-2], K // 8, 8, N)
    shifts = (jnp.arange(8, dtype=jnp.int32) * 4)[:, None]
    # nibble fields are disjoint, so sum == bitwise-or of the shifted nibbles
    packed = ((q & 0xF) << shifts).sum(axis=-2).astype(jnp.int32)
    return {Q4KEY: packed, SKEY: scale, DKEY: jnp.zeros((), w.dtype)}


def _dequantize_leaf_int4(node):
    import jax.numpy as jnp
    p = node[Q4KEY]
    shifts = (jnp.arange(8, dtype=jnp.int32) * 4)[:, None]
    v = (p[..., :, None, :] >> shifts) & 0xF          # [..., K//8, 8, N]
    v = v - 16 * (v >= 8)                             # sign-extend 4-bit 2's-comp
    q = v.reshape(*p.shape[:-2], p.shape[-2] * 8, p.shape[-1])
    return (q.astype(jnp.float32) * node[SKEY]).astype(node[DKEY].dtype)


def is_quantized_leaf(node) -> bool:
    return isinstance(node, dict) and (QKEY in node or Q4KEY in node)


def quantize_tree(params, min_size: int = 4096, bits: int = 8):
    """Quantize every floating leaf with ndim >= 2 and >= ``min_size`` elements
    (norm scales, biases and small tensors stay full precision — the
    reference's exclusion list). ``bits`` = 8 or 4; at 4, leaves whose
    contraction axis isn't a multiple of 8 (never true of transformer matmul
    weights) stay int8 rather than pay a padded unpack."""
    import jax.numpy as jnp
    if bits not in (8, 4):
        raise NotImplementedError(
            f"weight quantization supports bits=8 (int8) and bits=4 "
            f"(packed int4), got {bits}")

    def rec(node):
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        if (hasattr(node, "ndim") and node.ndim >= 2
                and jnp.issubdtype(node.dtype, jnp.floating)
                and int(np.prod(node.shape)) >= min_size):
            if bits == 4 and node.shape[-2] % 8 == 0:
                return _quantize_leaf_int4(node)
            return _quantize_leaf(node)
        return node

    return rec(params)


def dequantize_tree(params):
    """Collapse quantized subtrees back to full-precision arrays. Called inside
    jit: the unpack/convert/scale fuses into each weight's consumer, so the
    at-rest representation stays int8 / packed int4."""
    import jax.numpy as jnp

    def rec(node):
        if isinstance(node, dict) and Q4KEY in node:
            return _dequantize_leaf_int4(node)
        if is_quantized_leaf(node):
            return (node[QKEY].astype(jnp.float32) * node[SKEY]).astype(node[DKEY].dtype)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return node

    return rec(params)


def tree_nbytes(params) -> int:
    """Total array bytes in a (possibly quantized) tree — the memory claim."""
    import jax
    return sum(l.nbytes for l in jax.tree.leaves(params) if hasattr(l, "nbytes"))
