"""THE perf gate (tier-1, marker ``perfgate``): every flagship program must
lower under the CPU platform, stay inside its checked-in budget, and satisfy
the structural claims its feature shipped with (prefix caching saves bytes,
int4 shrinks weight traffic, ZeRO-3 actually communicates, bf16 paths carry
no f32 dots)."""

import pytest

from deepspeed_tpu.perf import gate
from deepspeed_tpu.perf.hlo_stats import stats_from_lowered
from deepspeed_tpu.perf.programs import FLAGSHIP_PROGRAMS, build_program

pytestmark = pytest.mark.perfgate


@pytest.fixture(scope="module")
def built_results():
    """Build + extract once per module: each program is an engine build plus
    an XLA compile, and the structural tests reuse the same artifacts."""
    out = {}
    for name in FLAGSHIP_PROGRAMS:
        built = build_program(name)
        result = gate.collect_stats(name, built=built)
        out[name] = (built, result)
    return out


@pytest.mark.parametrize("name", sorted(FLAGSHIP_PROGRAMS))
def test_flagship_program_within_budget(built_results, name):
    _, result = built_results[name]
    violations = gate.check_program(name, result.stats)
    assert not violations, "budget violations:\n" + "\n".join(str(v) for v in violations)


def test_zero3_train_batch_structure(built_results):
    _, result = built_results["zero3_train_batch"]
    s = result.stats
    ops = {c["op"] for c in s.collectives.values()}
    # ZeRO-3 on the 8-way data mesh: param gathers + grad reductions exist
    assert "all-gather" in ops and "all-reduce" in ops, s.collectives
    assert all(c["group_size"] == 8 for c in s.collectives.values())
    # bf16 compute path, fp32 master semantics: no f32 matmul anywhere
    assert s.f32_dot_count == 0
    assert s.dots_by_dtype.get("bf16", 0) > 0
    # analytic model flops attached => remat recompute ratio is reported
    assert s.recompute_ratio is not None and s.recompute_ratio > 0.5


def test_flash_fwd_bwd_structure(built_results):
    _, result = built_results["flash_attention_fwd_bwd"]
    assert result.stats.flops > 0
    assert result.stats.dot_count > 0
    assert result.roofline["step_s"] > 0


def test_paged_decode_step_structure(built_results):
    _, result = built_results["paged_decode_step"]
    # one device program for all 8 steps; it moves real bytes and fits v5e
    assert result.stats.bytes_accessed > 0
    assert result.roofline["fits_hbm"]


def test_spec_verify_step_costs_one_forward(built_results):
    built, result = built_results["spec_verify_step"]
    single = stats_from_lowered(built.comparisons["single_token_forward"],
                                name="single_token_forward")
    # the speculative claim, chip-independently: scoring a next-input token
    # plus k drafts is ONE forward at the same pad bucket — within noise of
    # the single-token decode step, nowhere near (1+k) sequential steps
    k1 = built.meta["feed_width"]
    assert result.stats.flops <= 1.10 * single.flops, \
        (result.stats.flops, single.flops)
    assert result.stats.flops < 0.5 * k1 * single.flops
    # all-position unembed: the verify program returns more logits bytes
    assert result.stats.output_bytes >= single.output_bytes
    assert result.stats.f32_dot_count == 0


def test_spec_tree_verify_costs_bounded_multiple_of_one_forward(built_results):
    built, result = built_results["spec_tree_verify"]
    single = stats_from_lowered(built.comparisons["single_token_forward"],
                                name="single_token_forward")
    linear = stats_from_lowered(built.comparisons["linear_verify"],
                                name="linear_verify")
    n = built.meta["tree_nodes"]
    # the tree-speculation claim, chip-independently: verifying a whole
    # draft tree (root + branches, tree-attention mask, per-query virtual
    # KV) is a BUDGETED multiple of one single-token forward at the same
    # bucket — the mask/gather overhead is priced, not silent — and nowhere
    # near node-count sequential decode steps
    assert result.stats.flops <= 1.5 * single.flops, \
        (result.stats.flops, single.flops)
    assert result.stats.flops < 0.25 * n * single.flops
    # same weight class as the linear verify program despite the tree mask
    assert result.stats.flops <= 1.5 * linear.flops
    # the greedy transfer win: per-node ids + hidden states cross the host
    # boundary, never a [T, vocab] f32 logits block
    assert result.stats.output_bytes < linear.output_bytes, \
        (result.stats.output_bytes, linear.output_bytes)
    assert result.stats.f32_dot_count == 0


def test_int4_decode_matmul_beats_bf16_weight_bytes(built_results):
    built, result = built_results["int4_decode_matmul"]
    bf16 = stats_from_lowered(built.comparisons["bf16_forward"], name="bf16_forward")
    # the int4 claim, chip-independently: packed weights shrink the bytes the
    # decode forward touches at rest (arguments = params + KV cache + batch)
    assert result.stats.argument_bytes < bf16.argument_bytes, \
        (result.stats.argument_bytes, bf16.argument_bytes)
    assert result.stats.f32_dot_count <= bf16.f32_dot_count + 1


def test_prefix_suffix_prefill_cheaper_than_full_prompt(built_results):
    built, result = built_results["prefix_suffix_prefill"]
    full = stats_from_lowered(built.comparisons["full_prompt_prefill"],
                              name="full_prompt_prefill")
    # the prefix-cache claim, chip-independently: prefilling only the suffix
    # is structurally cheaper than prefilling the whole prompt
    assert result.stats.flops < 0.5 * full.flops, (result.stats.flops, full.flops)
    assert result.stats.bytes_accessed < full.bytes_accessed


def test_gate_report_serializes_and_renders(built_results):
    from deepspeed_tpu.perf.reporting import render_gate_report
    report = gate.GateReport(chip="v5e")
    for name, (_, result) in built_results.items():
        result.violations = gate.check_program(name, result.stats)
        report.programs[name] = result
    doc = report.to_json()
    assert doc["ok"] is True
    assert set(doc["programs"]) == set(FLAGSHIP_PROGRAMS)
    text = render_gate_report(doc)
    for name in FLAGSHIP_PROGRAMS:
        assert name in text
    assert "within budgets" in text


def test_gate_publishes_perf_metrics():
    """perf_* families land on the registry when telemetry is active —
    exercised with a fabricated result (no program rebuild)."""
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.perf.budgets import Violation
    from deepspeed_tpu.perf.hlo_stats import HloStats
    from deepspeed_tpu.perf.roofline import predict
    from deepspeed_tpu.telemetry.catalog import METRIC_FAMILIES
    from deepspeed_tpu.telemetry.config import TelemetryConfig

    telemetry.shutdown()
    telemetry.state.registry = None
    try:
        telemetry.configure(TelemetryConfig(enabled=True))
        stats = HloStats(name="fake", flops=1e9, bytes_accessed=1e8, peak_bytes=123,
                         collective_bytes_total=64, f32_dot_count=0)
        result = gate.ProgramResult(name="fake", stats=stats,
                                    roofline=predict(stats, "v5e").to_dict(),
                                    violations=[Violation("fake", "flops", 2, 1, 1)])
        gate._publish_telemetry(result, "v5e")
        reg = telemetry.get_registry()
        registered = {name for (name, _) in reg._metrics}
        perf_names = {n for n in registered if n.startswith("perf_")}
        assert {"perf_gate_runs_total", "perf_gate_violations_total",
                "perf_program_flops", "perf_predicted_mfu_bound"} <= perf_names
        assert perf_names <= set(METRIC_FAMILIES)
        text = reg.render_prometheus()
        assert 'perf_program_flops{program="fake"}' in text
    finally:
        telemetry.shutdown()
        telemetry.state.registry = None
