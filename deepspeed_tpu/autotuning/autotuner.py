"""Autotuner: measured search over engine configurations.

Reference: ``deepspeed/autotuning/autotuner.py:42`` (Autotuner — profiles the
model, generates experiment configs from templates over ZeRO stage /
micro-batch / other knobs, schedules them through the launcher, picks the
fastest) with grid/random/model-based tuners under ``autotuning/tuner/``.

TPU formulation: experiments run in-process — each candidate config builds an
engine, times a few ``train_batch`` steps on the real backend, and is torn
down; XLA's compile cache keeps repeat shapes cheap. The search space follows
the reference's config schema (``autotuning`` block: ``tuner_type``
grid|random, ``max_experiments``, user-overridable space); results are
written to ``results.json`` like the reference's autotuning_metric_path.
"""

import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

DEFAULT_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8],
}

# the model-based tuner searches the reference's wider knob set
DEFAULT_MODEL_BASED_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8],
    "gradient_accumulation_steps": [1, 2, 4],
    "zero_optimization.offload_optimizer.device": ["none", "cpu"],
}


def _set_nested(cfg: dict, dotted: str, value):
    node = cfg
    keys = dotted.split(".")
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value


class Autotuner:

    def __init__(self, model, base_config: dict, batch_fn, model_parameters=None,
                 space: Optional[Dict[str, List[Any]]] = None, steps: int = 3,
                 warmup: int = 1, results_dir: Optional[str] = None):
        """``batch_fn(micro_batch_size) -> batch`` supplies a global batch for
        a candidate micro size (the reference reads it off the dataloader)."""
        self.model = model
        self.model_parameters = model_parameters
        self.base_config = base_config
        self.batch_fn = batch_fn
        at = base_config.get("autotuning", {})
        self.space = space or at.get("space", DEFAULT_SPACE)
        self.tuner_type = at.get("tuner_type", "gridsearch")
        self.max_experiments = at.get("max_experiments", 32)
        self.steps = steps
        self.warmup = warmup
        self.results_dir = results_dir or at.get("results_dir", "autotuning_results")
        self.results: List[dict] = []

    def _candidates(self):
        keys = list(self.space.keys())
        combos = list(itertools.product(*(self.space[k] for k in keys)))
        if self.tuner_type == "random":
            rng = np.random.default_rng(0)
            rng.shuffle(combos)
        return [dict(zip(keys, c)) for c in combos[:self.max_experiments]]

    def _run_experiment(self, overrides: dict) -> Optional[float]:
        import copy
        import jax
        import deepspeed_tpu
        from deepspeed_tpu.utils import groups

        cfg = copy.deepcopy(self.base_config)
        cfg.pop("autotuning", None)
        for k, v in overrides.items():
            _set_nested(cfg, k, v)
        micro = cfg.get("train_micro_batch_size_per_gpu", 1)
        try:
            groups.initialize_mesh(force=True)
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model, model_parameters=self.model_parameters, config=cfg)
            batch = self.batch_fn(micro)
            for _ in range(self.warmup):
                float(engine.train_batch(batch=batch))
            t0 = time.perf_counter()
            loss = None
            for _ in range(self.steps):
                loss = engine.train_batch(batch=batch)
            float(loss)
            dt = (time.perf_counter() - t0) / self.steps
            tput = engine.train_batch_size() / dt
            del engine
            return tput
        except Exception as e:
            logger.warning(f"autotuning experiment {overrides} failed: {str(e)[:120]}")
            return None

    def tune(self) -> dict:
        """Reference Autotuner.tune():404 — run the space, keep the fastest.
        ``tuner_type`` model_based routes through the cost-model search."""
        if self.tuner_type == "model_based":
            return self.tune_model_based()
        best = None
        for overrides in self._candidates():
            tput = self._run_experiment(overrides)
            rec = {"config": overrides, "throughput_samples_per_sec":
                   None if tput is None else round(tput, 2)}
            self.results.append(rec)
            logger.info(f"autotuning: {rec}")
            if tput is not None and (best is None or tput > best[1]):
                best = (overrides, tput)
        return self._write_results(best)

    def _write_results(self, best) -> dict:
        os.makedirs(self.results_dir, exist_ok=True)
        summary = {"experiments": self.results,
                   "best": None if best is None else
                   {"config": best[0], "throughput_samples_per_sec": round(best[1], 2)}}
        with open(os.path.join(self.results_dir, "results.json"), "w") as f:
            json.dump(summary, f, indent=2)
        if best is None:
            raise RuntimeError("autotuning: every experiment failed")
        return summary["best"]

    # --------------------------------------------------------- model-based --
    def _profile(self) -> dict:
        """One static profile pass (reference model_info_path role): parameter
        count + ZeRO degree + device HBM feed the analytic cost model."""
        import jax
        from deepspeed_tpu.autotuning.cost_model import device_memory_bytes
        from deepspeed_tpu.utils import groups

        if self.model_parameters is not None:
            n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(self.model_parameters))
        else:
            n_params = 0
        zero_degree = 1
        if groups.mesh_is_initialized():
            mesh = groups.get_mesh()
            zero_degree = int(np.prod([mesh.shape[ax] for ax in groups.get_zero_partition_axes()
                                       if ax in mesh.shape]))
        return {"n_params": n_params, "zero_degree": max(1, zero_degree),
                "hbm_bytes": device_memory_bytes()}

    def tune_model_based(self) -> dict:
        """Cost-model-guided search (reference tuner/model_based_tuner.py +
        cost_model.py): the analytic prior prunes OOM configs and orders the
        rest; after each measurement a ridge regression re-ranks the remaining
        candidates; stops at ``max_experiments`` or when the regressor predicts
        no remaining candidate beats the best measured. results.json records
        the estimate next to every measurement."""
        from deepspeed_tpu.autotuning.cost_model import AnalyticCostModel, LearnedCostModel

        space = self.space if self.space is not DEFAULT_SPACE else DEFAULT_MODEL_BASED_SPACE
        keys = list(space.keys())
        candidates = [dict(zip(keys, c)) for c in itertools.product(*(space[k] for k in keys))]

        prof = self._profile()
        prior = AnalyticCostModel(prof["n_params"], prof["zero_degree"], prof["hbm_bytes"])
        pruned = [c for c in candidates if not prior.fits(c)]
        candidates = [c for c in candidates if prior.fits(c)]
        for c in pruned:
            self.results.append({"config": c, "pruned": "predicted OOM",
                                 "predicted_bytes": int(prior.memory_bytes(c))})
        candidates.sort(key=prior.throughput_prior, reverse=True)

        learned = LearnedCostModel()
        best = None
        measured = 0
        while candidates and measured < self.max_experiments:
            if learned.trained:
                candidates.sort(key=learned.predict, reverse=True)
                # convergence: nothing left is predicted to beat the best
                if best is not None and learned.predict(candidates[0]) <= best[1]:
                    logger.info("autotuning(model_based): converged — no remaining "
                                "candidate predicted to beat the best measured")
                    break
            overrides = candidates.pop(0)
            predicted = learned.predict(overrides) if learned.trained else None
            tput = self._run_experiment(overrides)
            measured += 1
            rec = {"config": overrides,
                   "predicted_samples_per_sec": None if predicted is None else round(predicted, 2),
                   "prior_rank_score": round(prior.throughput_prior(overrides), 4),
                   "throughput_samples_per_sec": None if tput is None else round(tput, 2)}
            self.results.append(rec)
            logger.info(f"autotuning(model_based): {rec}")
            if tput is not None:
                learned.observe(overrides, tput)
                if best is None or tput > best[1]:
                    best = (overrides, tput)
        return self._write_results(best)
