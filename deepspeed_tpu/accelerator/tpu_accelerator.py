"""TPU accelerator implementation.

The north-star first deliverable (SURVEY.md §2.1): a ``TPU_Accelerator`` implementing
the ``DeepSpeedAccelerator`` surface with JAX/XLA semantics. Reference shape:
``accelerator/cuda_accelerator.py:26``.
"""

import contextlib
import functools
import os

import numpy as np

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


class _NoopStream:
    """XLA schedules compute/communication itself; streams are a compatibility shim."""

    def synchronize(self):
        import jax
        jax.effects_barrier()

    def wait_stream(self, other):
        ...


class _Event:
    """Host-time event; record() blocks on async dispatch (CUDA-event analog)."""

    def __init__(self, enable_timing=False, **kwargs):
        self.enable_timing = enable_timing
        self.time = None

    def record(self, stream=None):
        import jax, time
        jax.effects_barrier()
        self.time = time.time()

    def synchronize(self):
        ...

    def elapsed_time(self, end_event):
        return (end_event.time - self.time) * 1000.0

    def query(self):
        return self.time is not None


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        # Collectives lower through XLA over ICI/DCN; there is no user-visible
        # NCCL-style library, so the backend is named for the transport.
        self._communication_backend_name = "xla"
        self._compile_backend = "jax"
        self._seed = 0
        self._rng_key = None

    def _jax(self):
        import jax
        return jax

    # ---- device APIs -------------------------------------------------------------
    def is_synchronized_device(self):
        return False

    def device_name(self, device_index=None):
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index=None):
        jax = self._jax()
        devices = jax.local_devices()
        return devices[device_index or 0]

    def set_device(self, device_index):
        # SPMD: one process drives all local devices; nothing to select.
        ...

    def current_device(self):
        return 0

    def current_device_name(self):
        return "tpu:0"

    def device_count(self):
        return len(self._jax().local_devices())

    def global_device_count(self):
        return len(self._jax().devices())

    def synchronize(self, device_index=None):
        self._jax().effects_barrier()

    # ---- RNG APIs ----------------------------------------------------------------
    def random(self):
        import jax.random as jrandom
        return jrandom

    def _key(self):
        import jax
        if self._rng_key is None:
            self._rng_key = jax.random.PRNGKey(self._seed)
        return self._rng_key

    def set_rng_state(self, new_state, device_index=None):
        self._rng_key = new_state

    def get_rng_state(self, device_index=None):
        return self._key()

    def manual_seed(self, seed):
        import jax
        self._seed = int(seed)
        self._rng_key = jax.random.PRNGKey(self._seed)

    def manual_seed_all(self, seed):
        self.manual_seed(seed)

    def initial_seed(self):
        return self._seed

    def default_generator(self, device_index):
        return self._key()

    # ---- streams/events ----------------------------------------------------------
    def Stream(self, device=None, priority=0, **kwargs):
        return _NoopStream()

    @contextlib.contextmanager
    def stream(self, stream):
        yield

    def current_stream(self, device_index=None):
        return _NoopStream()

    def default_stream(self, device_index=None):
        return _NoopStream()

    def Event(self, **kwargs):
        return _Event(**kwargs)

    # ---- memory management -------------------------------------------------------
    def empty_cache(self):
        ...

    def _stats(self, device_index=None):
        dev = self.device(device_index)
        return dev.memory_stats() or {}

    def memory_allocated(self, device_index=None):
        return self._stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        return self._stats(device_index).get("peak_bytes_in_use", 0)

    def reset_max_memory_allocated(self, device_index=None):
        ...

    def memory_cached(self, device_index=None):
        return self.memory_allocated(device_index)

    def max_memory_cached(self, device_index=None):
        return self.max_memory_allocated(device_index)

    def reset_max_memory_cached(self, device_index=None):
        ...

    def memory_stats(self, device_index=None):
        return self._stats(device_index)

    def reset_peak_memory_stats(self, device_index=None):
        ...

    def memory_reserved(self, device_index=None):
        return self._stats(device_index).get("bytes_reserved", self.memory_allocated(device_index))

    def max_memory_reserved(self, device_index=None):
        return self.max_memory_allocated(device_index)

    def total_memory(self, device_index=None):
        return self._stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        return self.total_memory(device_index) - self.memory_allocated(device_index)

    # ---- dtype support -----------------------------------------------------------
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        # TPUs compute natively in bf16; fp16 arithmetic works but is not the
        # preferred path (kept for API parity with loss-scaling tests).
        return True

    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    # ---- misc --------------------------------------------------------------------
    def amp(self):
        return None

    def is_available(self):
        try:
            return len(self._jax().devices()) > 0
        except Exception:
            return False

    def range_push(self, msg):
        try:
            import jax.profiler
            self._trace_ctx = jax.profiler.TraceAnnotation(msg)
            self._trace_ctx.__enter__()
        except Exception:
            self._trace_ctx = None

    def range_pop(self):
        ctx = getattr(self, "_trace_ctx", None)
        if ctx is not None:
            ctx.__exit__(None, None, None)
            self._trace_ctx = None

    def lazy_call(self, callback):
        callback()

    def communication_backend_name(self):
        return self._communication_backend_name

    def is_triton_supported(self):
        return False

    # ---- graph operations --------------------------------------------------------
    def create_graph(self):
        # jit compilation is the graph capture mechanism; callers pass a callable.
        return None

    def capture_to_graph(self, graph, pool=None, stream=None):
        return contextlib.nullcontext()

    def replay_graph(self, graph):
        ...

    # ---- tensor factories --------------------------------------------------------
    def _factory(self, dtype):
        import jax.numpy as jnp

        def make(*shape):
            if len(shape) == 1 and isinstance(shape[0], (list, tuple, np.ndarray)):
                return jnp.asarray(shape[0], dtype=dtype)
            return jnp.zeros(shape, dtype=dtype)

        return make

    @property
    def BFloat16Tensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.bfloat16)

    @property
    def ByteTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.uint8)

    @property
    def DoubleTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.float64)

    @property
    def FloatTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.float32)

    @property
    def HalfTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.float16)

    @property
    def IntTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.int32)

    @property
    def LongTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.int64)

    def pin_memory(self, tensor, align_bytes=1):
        # Host numpy arrays are the pinned-staging representation on TPU hosts.
        return np.asarray(tensor)

    def is_pinned(self, tensor):
        return isinstance(tensor, np.ndarray)

    def on_accelerator(self, tensor):
        import jax
        return isinstance(tensor, jax.Array)

    # ---- op builder dispatch -----------------------------------------------------
    def op_builder_dir(self):
        return "deepspeed_tpu.op_builder.tpu"

    def create_op_builder(self, class_name):
        builder_class = self.get_op_builder(class_name)
        return builder_class() if builder_class is not None else None

    def get_op_builder(self, class_name):
        try:
            import importlib
            module = importlib.import_module(self.op_builder_dir())
            return getattr(module, class_name, None)
        except ImportError:
            return None

    def build_extension(self):
        from setuptools.command.build_ext import build_ext
        return build_ext

    def export_envs(self):
        return ["JAX_PLATFORMS", "XLA_FLAGS", "TPU_", "LIBTPU"]
