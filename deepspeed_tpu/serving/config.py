"""Serving config block.

Reference role: DeepSpeed-MII's deployment/``RaggedInferenceEngineConfig``
knobs for the persistent server (queue sizing, response behavior under load);
validated pydantic-style like the other config blocks (``config_v2.py``,
``telemetry/config.py``).
"""

from typing import Literal, Optional

from pydantic import Field, field_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel

DEFAULT_MAX_RESUME_BODY_BYTES = 2 << 30
"""One authority for the ``/v1/resume`` body bound — shared by
``ServingConfig``, ``FleetConfig`` and ``serving/server.py`` so the router
and a replica can never disagree on whether the same payload is admissible."""


class PrefixCacheConfig(DeepSpeedConfigModel):
    """Automatic prefix caching (radix-tree KV reuse with copy-on-write block
    sharing — ``inference/v2/ragged/prefix_cache.py``). Off by default: the
    trie pins finished sequences' prefix blocks, so a cache-enabled scheduler
    intentionally does NOT return the KV pool to empty between requests."""

    enabled: bool = False
    """Look up every admitted prompt's longest cached prefix and publish
    completed sequences' full blocks back to the trie."""

    max_blocks: Optional[int] = Field(None, ge=1)
    """Cap on device blocks the trie may pin; None = bounded only by the pool
    (the KV-pressure path evicts unreferenced trie leaves LRU-first before
    touching live sequences)."""

    min_prefix_blocks: int = Field(1, ge=1)
    """Smallest cached-prefix match (in blocks) worth applying to a request;
    shorter matches prefill cold."""


class ServingConfig(DeepSpeedConfigModel):
    """Knobs for the request scheduler + HTTP front-end."""

    queue_capacity: int = Field(128, ge=1)
    """Maximum QUEUED (admitted-but-unscheduled) requests; beyond it the
    backpressure policy applies."""

    backpressure: Literal["reject", "block"] = "reject"
    """Queue-full behavior: ``reject`` fails ``submit()`` immediately (HTTP
    429); ``block`` stalls the submitting thread until space frees (the
    closed-loop client pattern)."""

    default_max_new_tokens: int = Field(64, ge=1)
    """Per-request cap when the request doesn't specify one."""

    default_deadline_s: Optional[float] = Field(None, gt=0)
    """Deadline applied to requests that don't carry their own; None = no
    deadline (requests are bounded by max_new_tokens only)."""

    drain_timeout_s: float = Field(30.0, ge=0)
    """Graceful-shutdown budget: how long ``stop(drain=True)`` lets in-flight
    requests finish before cancelling the remainder."""

    scheduler_tick_s: float = Field(0.001, gt=0)
    """Idle sleep between scheduler iterations when there is no work; busy
    iterations run back-to-back."""

    decode_chunk: int = Field(1, ge=1)
    """Decode steps per device dispatch on the decode-only fast path
    (``engine.decode_loop``); >1 trades up-to-(K-1)-token speculative
    over-generation for one host round-trip per K tokens."""

    max_prefill_chunk: Optional[int] = Field(None, ge=1)
    """Cap on prompt tokens admitted per batch per request (Dynamic SplitFuse
    chunk size); None = bounded only by the engine's ragged token budget."""

    heartbeat_interval_s: float = Field(0.05, ge=0)
    """How often an *idle* scheduler runs ``engine.empty_run()`` so EP
    replicas stay in collective lock-step. 0 = every idle tick."""

    heartbeat_enabled: Optional[bool] = None
    """None = auto (heartbeat only when the engine has expert parallelism
    enabled); True/False force it."""

    sse_keepalive_s: float = Field(10.0, gt=0)
    """SSE comment-line cadence while a stream has no token to send (queue
    wait, long prefill): keeps the socket demonstrably alive so a fleet
    router's bounded read budget (``FleetConfig.read_timeout_s``) measures
    replica *death*, never mere load."""

    host: str = "127.0.0.1"
    port: int = Field(0, ge=0, le=65535)
    """Bind address for ``ServingServer``; port 0 = ephemeral (the bound
    address is on ``server.address`` after ``start()``)."""

    prefix_cache: PrefixCacheConfig = PrefixCacheConfig()
    """Automatic prefix caching over the paged KV cache (radix-tree reuse +
    copy-on-write sharing); see :class:`PrefixCacheConfig`."""

    max_resume_body_bytes: int = Field(DEFAULT_MAX_RESUME_BODY_BYTES, gt=0)
    """Upper bound on a ``POST /v1/resume`` body (the base64 KV-handoff
    payload; real-model KV runs to hundreds of MB and base64 adds 4/3). The
    body is fully buffered per handler thread, so operators whose resume
    endpoint is reachable beyond fleet-internal traffic should lower this to
    their largest expected payload."""

    @field_validator("default_deadline_s")
    @classmethod
    def _deadline_finite(cls, v):
        if v is not None and not (v > 0 and v == v):  # rejects NaN too
            raise ValueError("default_deadline_s must be a positive number")
        return v
