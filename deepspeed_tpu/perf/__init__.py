"""Chip-independent perf gates: static HLO cost/roofline analysis.

The TPU tunnel has been dead since BENCH_r03, so on-chip numbers cannot be
the regression fence for the flagship kernels. This subsystem makes perf
claims *structural* instead: every flagship computation (ZeRO-3
``train_batch``, flash fwd+bwd, the paged ``decode_loop`` step, the int4
decode matmul, the prefix-cache suffix prefill) is lowered under
``JAX_PLATFORMS=cpu``, and facts XLA itself reports — FLOPs, bytes moved,
live-buffer peak, collective payloads, fusion counts, dot dtypes — are
ratcheted against checked-in budget files in tier-1.

Layers:

- :mod:`~deepspeed_tpu.perf.hlo_stats` — extraction: lowered program →
  :class:`HloStats` (cost_analysis + memory_analysis + StableHLO/compiled
  HLO text parsing);
- :mod:`~deepspeed_tpu.perf.chip_specs` — per-chip peak specs (v5e first);
- :mod:`~deepspeed_tpu.perf.roofline` — :class:`HloStats` × chip spec →
  predicted step time / MFU upper bound and the binding resource;
- :mod:`~deepspeed_tpu.perf.budgets` — the ratchet: budget JSON files,
  violation checking, deliberate re-baselining;
- :mod:`~deepspeed_tpu.perf.programs` — builders for the flagship
  programs, via the engines' official lowering hooks
  (``lowerable_callables`` / ``lower_*``);
- :mod:`~deepspeed_tpu.perf.gate` — the tier-1 pytest harness
  (``-m perfgate``) plus the ``bin/dstpu_perfgate`` CLI entry points.
"""

from deepspeed_tpu.perf.budgets import (Budget, Violation, budget_from_stats, check_stats,
                                        load_budget, write_budget)
from deepspeed_tpu.perf.chip_specs import CHIP_SPECS, ChipSpec, get_chip_spec
from deepspeed_tpu.perf.hlo_stats import (CollectiveStats, HloStats, stats_from_callable,
                                          stats_from_lowered)
from deepspeed_tpu.perf.roofline import RooflinePrediction, predict

__all__ = [
    "Budget", "Violation", "budget_from_stats", "check_stats", "load_budget",
    "write_budget", "CHIP_SPECS", "ChipSpec", "get_chip_spec", "CollectiveStats",
    "HloStats", "stats_from_callable", "stats_from_lowered", "RooflinePrediction",
    "predict",
]
