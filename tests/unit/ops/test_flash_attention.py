"""Flash attention kernel tests (reference: tests/unit/inference/v2/modules/
test_blocked_attn.py compares against a flash reference; here Pallas vs jnp)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.llama import causal_attention
from deepspeed_tpu.ops.pallas.flash_attention import _blockwise_attention_ref, flash_attention


def _rand_qkv(B, S, H, D, kvh=None, seed=0, dtype=jnp.float32):
    rng = jax.random.PRNGKey(seed)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, kvh or H, D), dtype)
    v = jax.random.normal(kv, (B, S, kvh or H, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    B, S, H, D = 2, 256, 4, 32
    q, k, v = _rand_qkv(B, S, H, D)
    scale = 1.0 / np.sqrt(D)
    out = flash_attention(q, k, v, scale, causal)
    if causal:
        ref = causal_attention(q, k, v, scale)
    else:
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        probs = jax.nn.softmax(logits, axis=-1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_gqa():
    B, S, H, D = 1, 128, 8, 16
    q, k, v = _rand_qkv(B, S, H, D, kvh=2)
    out = flash_attention(q, k, v, 1.0 / np.sqrt(D), True)
    ref = causal_attention(q, k, v, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_gradients_match_dense():
    B, S, H, D = 1, 128, 2, 16
    q, k, v = _rand_qkv(B, S, H, D)
    scale = 1.0 / np.sqrt(D)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, scale, True)**2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v, scale)**2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)


def test_flash_gqa_gradients():
    B, S, H, D = 1, 128, 4, 16
    q, k, v = _rand_qkv(B, S, H, D, kvh=2)
    scale = 1.0 / np.sqrt(D)

    gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(q, k, v, scale, True)**2),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(causal_attention(q, k, v, scale)**2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)


def test_flash_irregular_seq_lengths():
    """S not divisible by default blocks: forward AND backward must still match
    dense (regression: bwd used to drop the tail KV block at S=300)."""
    for S in (300, 96, 192):
        B, H, D = 1, 2, 16
        q, k, v = _rand_qkv(B, S, H, D, seed=S)
        scale = 1.0 / np.sqrt(D)
        out = flash_attention(q, k, v, scale, True)
        ref = causal_attention(q, k, v, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)
        gf = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, scale, True)**2))(q)
        gd = jax.grad(lambda q: jnp.sum(causal_attention(q, k, v, scale)**2))(q)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), rtol=2e-3, atol=2e-3)


def test_blockwise_ref_matches_dense():
    B, S, H, D = 2, 128, 2, 16
    q, k, v = _rand_qkv(B, S, H, D)
    scale = 1.0 / np.sqrt(D)
    out = _blockwise_attention_ref(q, k, v, scale, True, block_k=32)
    ref = causal_attention(q, k, v, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_llama_flash_flag():
    from deepspeed_tpu.models import llama
    cfg = llama.LlamaConfig.tiny(use_flash_attention=True)
    model, params = llama.init_params(cfg, batch_size=2, seq_len=128)
    ids = jnp.zeros((2, 128), jnp.int32)
    loss = model.apply({"params": params}, (ids, ids))
    cfg2 = llama.LlamaConfig.tiny()
    model2 = llama.LlamaForCausalLM(cfg2)
    loss2 = model2.apply({"params": params}, (ids, ids))
    np.testing.assert_allclose(float(loss), float(loss2), rtol=5e-3)


def test_pallas_backward_matches_manual_oracle():
    """The hand dq/dk/dv kernels must agree with the blockwise-JAX oracle
    (and, transitively via test_flash_gradients_match_dense, with autodiff)."""
    import deepspeed_tpu.ops.pallas.flash_attention as fa

    rng = np.random.default_rng(11)
    B, S, H, D = 2, 256, 3, 128
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)

    def loss(q, k, v):
        return (fa.flash_attention(q, k, v, 1.0 / np.sqrt(D), True) ** 2).sum()

    gp = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    fa._FORCE_MANUAL_BWD = True
    try:
        gm = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        fa._FORCE_MANUAL_BWD = False
    for a, b, nm in zip(gp, gm, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{nm}")


def test_pallas_backward_noncausal_and_gqa():
    import deepspeed_tpu.ops.pallas.flash_attention as fa

    rng = np.random.default_rng(12)
    B, S, H, KVH, D = 1, 128, 4, 2, 128
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KVH, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KVH, D)), jnp.float32)

    def loss_fa(q, k, v):
        return (fa.flash_attention(q, k, v, 1.0 / np.sqrt(D), False) ** 2).sum()

    def loss_ref(q, k, v):
        ke, ve = fa._expand_gqa(q, k, v)
        return (fa._blockwise_attention_ref(q, ke, ve, 1.0 / np.sqrt(D), False) ** 2).sum()

    ga = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(ga, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{nm}")
