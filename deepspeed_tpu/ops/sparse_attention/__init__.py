from deepspeed_tpu.ops.sparse_attention.sparsity_config import (BigBirdSparsityConfig,
                                                                BSLongformerSparsityConfig,
                                                                DenseSparsityConfig,
                                                                FixedSparsityConfig,
                                                                LocalSlidingWindowSparsityConfig,
                                                                SparsityConfig,
                                                                VariableSparsityConfig)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (SparseSelfAttention,
                                                                      layout_to_dense_mask,
                                                                      sparse_self_attention)

__all__ = [
    "SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig", "VariableSparsityConfig",
    "BigBirdSparsityConfig", "BSLongformerSparsityConfig", "LocalSlidingWindowSparsityConfig",
    "SparseSelfAttention", "sparse_self_attention", "layout_to_dense_mask",
]
