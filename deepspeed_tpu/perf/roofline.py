"""Roofline model: HLO facts × chip spec → predicted step-time bounds.

The prediction is a *lower bound* on step time (and therefore an *upper
bound* on MFU): each resource — MXU FLOPs, HBM bytes, ICI collective bytes —
is assumed perfectly overlapped with the others, so the step can be no
faster than the busiest resource. That is exactly the right direction for a
gate: a program whose predicted bound regresses has structurally more work
on some resource, whatever a real chip would measure.
"""

from dataclasses import dataclass

from deepspeed_tpu.perf.chip_specs import DEFAULT_CHIP, ChipSpec, get_chip_spec
from deepspeed_tpu.perf.hlo_stats import HloStats


@dataclass
class RooflinePrediction:
    chip: str
    compute_s: float            # flops / peak
    memory_s: float             # bytes accessed / HBM bandwidth
    collective_s: float         # collective payload / ICI bandwidth
    step_s: float               # max of the three (perfect overlap)
    bound: str                  # which resource binds: compute|memory|collective
    mfu_bound: float            # highest achievable MFU for this program
    arithmetic_intensity: float  # flops per byte accessed
    fits_hbm: bool              # live-buffer peak vs chip HBM capacity

    def to_dict(self) -> dict:
        return dict(chip=self.chip, compute_s=self.compute_s, memory_s=self.memory_s,
                    collective_s=self.collective_s, step_s=self.step_s, bound=self.bound,
                    mfu_bound=self.mfu_bound,
                    arithmetic_intensity=self.arithmetic_intensity,
                    fits_hbm=self.fits_hbm)


def predict(stats: HloStats, chip="v5e") -> RooflinePrediction:
    """Predict the step-time bound for ``stats`` on ``chip`` (a name from
    :data:`~deepspeed_tpu.perf.chip_specs.CHIP_SPECS` or a
    :class:`~deepspeed_tpu.perf.chip_specs.ChipSpec`)."""
    spec = chip if isinstance(chip, ChipSpec) else get_chip_spec(chip or DEFAULT_CHIP)
    compute_s = stats.flops / spec.peak_bf16_flops
    memory_s = stats.bytes_accessed / spec.hbm_bytes_per_s
    collective_s = stats.collective_bytes_total / spec.ici_bytes_per_s
    step_s = max(compute_s, memory_s, collective_s)
    if step_s <= 0.0:
        bound, mfu = "none", 0.0
    else:
        # explicit max over (label, time): a dict keyed by times would
        # collapse exact ties and mislabel the binding resource
        bound = max((("compute", compute_s), ("memory", memory_s),
                     ("collective", collective_s)), key=lambda kv: kv[1])[0]
        # MFU against the ANALYTIC flops when the program declared them (the
        # PaLM-convention model flops), else against the HLO count — remat
        # recompute then counts as useful work, which overstates MFU; callers
        # wanting the honest number supply analytic_flops
        useful = stats.analytic_flops if stats.analytic_flops else stats.flops
        mfu = useful / (step_s * spec.peak_bf16_flops)
    return RooflinePrediction(
        chip=spec.name, compute_s=compute_s, memory_s=memory_s,
        collective_s=collective_s, step_s=step_s, bound=bound, mfu_bound=mfu,
        arithmetic_intensity=(stats.flops / stats.bytes_accessed
                              if stats.bytes_accessed else 0.0),
        fits_hbm=stats.peak_bytes <= spec.hbm_bytes)
