"""Sequence + KV-cache state manager.

Reference: ``deepspeed/inference/v2/ragged/ragged_manager.py`` (DSStateManager:19 —
uid → DSSequenceDescriptor tracking over a BlockedKVCache).
"""

from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache
from deepspeed_tpu.inference.v2.ragged.manager_configs import DSStateManagerConfig, KVCacheConfig
from deepspeed_tpu.inference.v2.ragged.sequence_descriptor import DSSequenceDescriptor
from deepspeed_tpu.utils.logging import logger


class DSStateManager:

    def __init__(self, config: DSStateManagerConfig, kv_config: KVCacheConfig, mp_group=None):
        self._config = config
        self._kv_config = kv_config
        self._seqs: Dict[int, DSSequenceDescriptor] = {}
        self._offloaded: Dict[int, int] = {}  # uid -> host-pool handle
        self._kv_cache = BlockedKVCache(kv_config, config.memory_config, mp_group=mp_group,
                                        offload=config.offload,
                                        offload_path=config.offload_path)

    # ------------------------------------------------------------- sequences --
    def get_sequence(self, uid: int) -> Optional[DSSequenceDescriptor]:
        return self._seqs.get(uid)

    def get_or_create_sequence(self, uid: int) -> DSSequenceDescriptor:
        seq = self._seqs.get(uid)
        if seq is not None:
            return seq
        return self._create_sequence(uid)

    def _create_sequence(self, uid: int) -> DSSequenceDescriptor:
        if uid in self._seqs:
            raise ValueError(f"sequence {uid} already tracked")
        if self.n_tracked_sequences >= self._config.max_tracked_sequences:
            raise RuntimeError(f"max_tracked_sequences={self._config.max_tracked_sequences} reached")
        max_blocks = (self._config.max_context + self._kv_config.block_size - 1) // self._kv_config.block_size
        seq = DSSequenceDescriptor(uid, max_blocks_per_seq=max_blocks)
        self._seqs[uid] = seq
        return seq

    def create_cached_sequence(self, uid: int, blocks, seen_tokens: int) -> DSSequenceDescriptor:
        """Create a sequence whose block table arrives **pre-populated** — the
        prefix-cache hit path: ``blocks`` already hold the KV for the first
        ``seen_tokens`` committed tokens (shared, read-only; the caller holds
        one reference per block on this sequence's behalf, which
        ``flush_sequence`` returns). The next forward continues at position
        ``seen_tokens`` exactly like a restored or imported sequence."""
        blocks = np.atleast_1d(np.asarray(blocks)).astype(np.int64)
        seen_tokens = int(seen_tokens)
        if seen_tokens < 0 or seen_tokens > blocks.size * self._kv_config.block_size:
            raise ValueError(
                f"create_cached_sequence: seen_tokens={seen_tokens} does not fit "
                f"{blocks.size} blocks of {self._kv_config.block_size} tokens")
        seq = self._create_sequence(uid)
        try:
            if blocks.size:
                seq.extend_kv_cache(blocks)
            seq.pre_forward(seen_tokens)
            seq.post_forward()
        except Exception:
            del self._seqs[uid]  # the caller still owns the block references
            raise
        return seq

    def flush_sequence(self, uid: int) -> None:
        """Release all state for a sequence (reference ragged_manager.py:110)."""
        seq = self._seqs.pop(uid, None)
        if seq is None:
            logger.warning(f"flush_sequence: unknown uid {uid}")
            return
        handle = self._offloaded.pop(uid, None)
        if handle is not None:
            self._kv_cache.drop_offloaded(handle)
        elif seq.cur_allocated_blocks > 0:
            self._kv_cache.free(seq.kv_blocks)

    # ----------------------------------------------------------- kv offload --
    def is_offloaded(self, uid: int) -> bool:
        return uid in self._offloaded

    def sequence_tier(self, uid: int) -> str:
        """Which tier of the KV ladder currently holds ``uid``'s cache:
        ``device`` for a resident block table, else the tiered store's answer
        (``host`` | ``disk``) for the offloaded payload."""
        handle = self._offloaded.get(uid)
        if handle is None:
            return "device"
        return self._kv_cache.offload_tier(handle)

    def offload_sequence(self, uid: int) -> None:
        """Evict a (cold) sequence's KV blocks to the host tier, freeing its
        device blocks for other sequences. The sequence stays tracked; the
        next forward that touches it restores it (engine put/decode_loop)."""
        seq = self._seqs.get(uid)
        if seq is None:
            raise ValueError(f"offload_sequence: unknown uid {uid}")
        if uid in self._offloaded:
            return
        if seq.in_flight_tokens:
            raise RuntimeError(f"offload_sequence: uid {uid} has in-flight tokens")
        if seq.cur_allocated_blocks == 0:
            return
        self._offloaded[uid] = self._kv_cache.offload(seq.kv_blocks)
        seq.kv_tier = self.sequence_tier(uid)

    def demote_sequence(self, uid: int, wait: bool = False) -> bool:
        """Push an already-offloaded sequence one tier colder (host→disk);
        returns whether a demotion was scheduled. The brownout controller's
        demote-before-shed stage calls this for the coldest offloaded
        sessions before any queued work is shed."""
        handle = self._offloaded.get(uid)
        if handle is None:
            return False
        demoted = self._kv_cache.demote_offloaded(handle, wait=wait)
        if demoted:
            seq = self._seqs.get(uid)
            if seq is not None:
                seq.kv_tier = "disk" if wait else self.sequence_tier(uid)
        return demoted

    def restore_sequence(self, uid: int) -> None:
        """Bring an offloaded sequence's KV back into fresh device blocks and
        rewrite its block table. Raises if the device pool cannot hold it
        (offload other sequences first)."""
        handle = self._offloaded.pop(uid, None)
        if handle is None:
            return
        try:
            new_blocks = self._kv_cache.restore(handle)
        except Exception:
            self._offloaded[uid] = handle  # payload intact; caller may evict + retry
            raise
        seq = self._seqs[uid]
        seq.replace_kv_blocks(new_blocks)
        seq.kv_tier = "device"

    # ------------------------------------------------------------ kv handoff --
    def export_sequence(self, uid: int) -> dict:
        """Portable snapshot of a tracked sequence — committed-token count plus
        KV-block contents — for :meth:`import_sequence` on another manager (the
        fleet prefill→decode handoff; bytes framing lives in
        ``ragged/handoff.py``). An offloaded sequence is restored first (its
        payload is already host-side, but export must observe one canonical
        path). The sequence stays tracked and resident here; the caller
        flushes once the recipient has taken over."""
        seq = self._seqs.get(uid)
        if seq is None:
            raise ValueError(f"export_sequence: unknown uid {uid}")
        if seq.in_flight_tokens:
            raise RuntimeError(f"export_sequence: uid {uid} has in-flight tokens")
        if uid in self._offloaded:
            self.restore_sequence(uid)
        kv = (self._kv_cache.gather_blocks(seq.kv_blocks)
              if seq.cur_allocated_blocks > 0 else None)
        return {"uid": uid, "seen_tokens": seq.seen_tokens, "kv": kv}

    def import_sequence(self, snapshot: dict, uid: Optional[int] = None) -> int:
        """Recreate an exported sequence under ``uid`` (default: the donor's
        uid): fresh device blocks, contents written back, committed-token
        count restored. Raises without consuming anything when the uid is
        already tracked, the payload's geometry doesn't fit this cache, or
        the device pool can't hold it (evict and retry)."""
        uid = int(snapshot["uid"] if uid is None else uid)
        if uid in self._seqs:
            raise ValueError(f"import_sequence: uid {uid} already tracked")
        kv = snapshot["kv"]
        seq = self._create_sequence(uid)
        try:
            if kv is not None:
                if kv.shape[2] > seq.max_blocks:
                    raise ValueError(
                        f"import_sequence: payload holds {kv.shape[2]} blocks; "
                        f"this manager caps sequences at {seq.max_blocks} "
                        f"(max_context={self._config.max_context})")
                seq.extend_kv_cache(self._kv_cache.scatter_blocks(kv))
            seq.pre_forward(int(snapshot["seen_tokens"]))
            seq.post_forward()
        except Exception:
            del self._seqs[uid]  # scatter freed its blocks on failure
            raise
        return uid

    @property
    def tracked_sequences(self) -> Dict[int, DSSequenceDescriptor]:
        return self._seqs

    @property
    def n_tracked_sequences(self) -> int:
        return len(self._seqs)

    # --------------------------------------------------------------- kv cache --
    @property
    def kv_cache(self) -> BlockedKVCache:
        return self._kv_cache

    @property
    def kv_block_size(self) -> int:
        return self._kv_config.block_size

    @property
    def free_blocks(self) -> int:
        return self._kv_cache.free_blocks

    def allocate_blocks(self, n_blocks: int):
        return self._kv_cache.reserve(n_blocks)
