"""Standalone activation-checkpointing API.

Reference: ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
(``configure:871``, ``checkpoint:748`` — CheckpointFunction with partitioned
activations across TP ranks, optional CPU checkpointing, contiguous buffers,
RNG state tracking).

TPU mapping (each knob → its XLA-era mechanism):

- ``checkpoint(fn, *args)`` → ``jax.checkpoint`` (remat): recompute in the
  backward instead of storing; RNG correctness is automatic (same key re-used
  on recompute — the role of the reference's CudaRNGStatesTracker).
- ``partition_activations`` → save the dot outputs instead of nothing; under
  TP/ZeRO shardings those saved residuals are already partitioned arrays, so
  each rank stores only its shard (the reference's partition-then-allgather).
- ``cpu_checkpointing`` → offload saved dot products to pinned host memory
  when the backend supports it (``offload_dot_products_to_host``), the
  reference's CPU checkpoint buffer.
- ``contiguous_memory_optimization``/``number_checkpoints``/``profile`` are
  accepted for config parity; XLA's allocator already packs remat buffers.
"""

from typing import Optional

from deepspeed_tpu.utils.logging import logger

_CONFIG = None


def _policy():
    import jax
    if _CONFIG is None:
        return jax.checkpoint_policies.nothing_saveable
    if _CONFIG.cpu_checkpointing:
        cp = getattr(jax.checkpoint_policies, "offload_dot_products_to_host", None)
        if cp is not None:
            from deepspeed_tpu.runtime.zero.offload import host_memory_kind
            return cp("device", host_memory_kind())
        logger.warning("cpu_checkpointing: this jax has no host-offload remat policy; "
                       "saving dot products on device instead")
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if _CONFIG.partition_activations:
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    return jax.checkpoint_policies.nothing_saveable


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference checkpointing.py:871 — flags override the config block."""
    global _CONFIG
    from deepspeed_tpu.runtime.config import ActivationCheckpointingConfig, DeepSpeedConfig

    if deepspeed_config is not None:
        if isinstance(deepspeed_config, DeepSpeedConfig):
            _CONFIG = deepspeed_config.activation_checkpointing_config
        else:
            _CONFIG = DeepSpeedConfig(deepspeed_config).activation_checkpointing_config
    elif _CONFIG is None:
        _CONFIG = ActivationCheckpointingConfig()
    if partition_activations is not None:
        _CONFIG.partition_activations = partition_activations
    if checkpoint_in_cpu is not None:
        _CONFIG.cpu_checkpointing = checkpoint_in_cpu
    if num_checkpoints is not None:
        _CONFIG.number_checkpoints = num_checkpoints
    if contiguous_checkpointing is not None:
        _CONFIG.contiguous_memory_optimization = contiguous_checkpointing
    if profile is not None:
        _CONFIG.profile = profile


def is_configured() -> bool:
    return _CONFIG is not None


def reset():
    """Reference checkpointing.py:999 (buffer reset) + test isolation."""
    global _CONFIG
    _CONFIG = None


def checkpoint(function, *args):
    """Rematerialized call of ``function(*args)`` (reference checkpoint:748).

    Differentiable; the saved-residual policy follows :func:`configure`.
    """
    import jax
    return jax.checkpoint(function, policy=_policy())(*args)


def checkpoint_wrapped(function):
    """The transform itself (for wrapping layers once, not per call)."""
    import jax
    return jax.checkpoint(function, policy=_policy())


# RNG-tracker parity surface: jax.checkpoint replays the same PRNG keys on
# recompute, so these are well-defined no-ops kept for API compatibility.
def model_parallel_cuda_manual_seed(seed: int):
    logger.info("model_parallel_cuda_manual_seed: PRNG keys are explicit under JAX; "
                "remat replays them automatically")


def get_cuda_rng_tracker():
    return None
